"""Tiered KV: host offload, session hibernation, and a restart-surviving
prefix store (ISSUE 7 tentpole).

Before this module the KV tier ladder had exactly one rung: a session (or
radix-cache leaf) lived in the HBM page pool until ``SessionStore.alloc``'s
eviction ladder destroyed it, and the next touch paid a full re-prefill.
Agent sessions spend most of their wall-clock WAITING — on actions, on
children, on wait-timers (PAPERS.md "Stateful Inference for Low-Latency
Multi-Agent Tool Calling") — so at any instant most resident pages belong
to nobody who is decoding. Host-memory offload is the standard TPU-serving
answer to that capacity wall (PAPERS.md Gemma-on-TPU serving): HBM holds
the working set, host RAM holds the parked set, disk holds what should
survive the process.

Three tiers, managed by :class:`TierManager` (one per engine/SessionStore):

  HBM   — the device page pool (models/generate.py SessionStore). Unchanged
          semantics; still the only tier attention can read.
  HOST  — :class:`HostPageStore`: numpy copies of demoted sessions and
          stripped prefix-cache leaves, LRU-bounded by ``host_bytes``.
          Eviction from HBM stops being destruction: ``alloc``'s ladder
          DEMOTES here (one ``device_get`` per victim) before releasing
          pages, and a demoted session touched again RESTORES by page-in
          (``device_put`` + the pool scatter the serving path already
          uses) instead of re-prefilling. Refcounts for shared/COW pages
          are untouched: demote copies content and releases only the
          victim's own references, so adopters and the radix tree keep
          reading the still-resident originals (prefix_cache.py I1/I2).
  DISK  — :class:`DiskPrefixStore`: checksummed page-aligned prefix
          blocks under ``disk_dir``. Prefix-cache inserts persist their
          blocks (dedup by content hash), so a RESTARTED process lazily
          warms from its predecessor's prefixes: a radix-tree miss falls
          through to host then disk, pages in, and re-inserts the block.
          Corrupt entries (crc mismatch, torn writes) are skipped and
          unlinked — a bad file must never poison a serving prefix.

Restore invariant (tier-1 tested): a hibernated-and-restored session is
BIT-IDENTICAL to one that never left HBM — device_get/device_put round a
page's bytes exactly, the restored session re-enters the store with the
same tokens/start_pos, and the LCP resume path neither knows nor cares
where the pages spent the interim. Temp-0 outputs therefore match exactly
with tiering on or off.

Locking: demote runs inside ``SessionStore.alloc`` (store lock held, and
the engine's ``_paged_lock`` held by every sessioned caller — the pool
arrays are only ever touched under it). Restore is called from the
engine's session-lookup path (same locks) or from ``prefetch`` (which
try-acquires the engine lock itself, so a busy engine skips the warm-up
rather than blocking the submitter — the generate path restores
synchronously anyway). Disk writes NEVER happen under those locks:
demote/persist only copy device pages host-side (one ``device_get`` per
victim — unavoidable, the pages are about to be recycled) and queue the
npz write to a daemon spill writer; ``flush_spills`` drains it when a
caller needs durability (tests, orderly shutdown).
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import queue
import threading
import time
import zlib
from collections import OrderedDict
from typing import Optional, Sequence

import jax
import numpy as np

from quoracle_tpu.analysis.lockdep import named_lock

logger = logging.getLogger(__name__)


def _round_up_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_pages(k_pool, v_pool, k_host, v_host, pages):
    """Page-in: host block KV → pool pages in place (pools donated, same
    aliasing discipline as generate.py's step_scatter_prompt). ``pages``
    may be padded with 0 — page 0 is scratch by construction, so padded
    writes land harmlessly."""
    k_pool = k_pool.at[:, pages].set(k_host.astype(k_pool.dtype))
    v_pool = v_pool.at[:, pages].set(v_host.astype(v_pool.dtype))
    return k_pool, v_pool


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_pages_q(k_pool, v_pool, ks_pool, vs_pool, k_host, v_host,
                     ks_host, vs_host, pages):
    """Quantized page-in (ISSUE 13): int8 payload pages AND their fp32
    scale blocks land together — a restored page is byte-identical to
    the demoted one, scales included."""
    k_pool = k_pool.at[:, pages].set(k_host.astype(k_pool.dtype))
    v_pool = v_pool.at[:, pages].set(v_host.astype(v_pool.dtype))
    ks_pool = ks_pool.at[:, pages].set(ks_host.astype(ks_pool.dtype))
    vs_pool = vs_pool.at[:, pages].set(vs_host.astype(vs_pool.dtype))
    return k_pool, v_pool, ks_pool, vs_pool


class _HostSession:
    __slots__ = ("tokens", "start_pos", "k", "v", "k_scale", "v_scale",
                 "nbytes", "ts")

    def __init__(self, tokens, start_pos, k, v, k_scale=None,
                 v_scale=None):
        self.tokens = tokens
        self.start_pos = start_pos
        self.k = k                      # np [L, n_pages, page, KV, HD]
        self.v = v
        # int8 entries (ISSUE 13): fp32 [L, n_pages, KV, page] — the
        # scales travel WITH the pages through every tier move
        self.k_scale = k_scale
        self.v_scale = v_scale
        from quoracle_tpu.models.quant import entry_nbytes
        self.nbytes = entry_nbytes(k, v, k_scale, v_scale)
        self.ts = time.monotonic()


class _HostBlock:
    __slots__ = ("tokens", "k", "v", "k_scale", "v_scale", "nbytes",
                 "ts")

    def __init__(self, tokens, k, v, k_scale=None, v_scale=None):
        self.tokens = tokens            # full token prefix (page-aligned)
        self.k = k                      # np [L, page, KV, HD]
        self.v = v
        self.k_scale = k_scale          # np [L, KV, page] (int8 entries)
        self.v_scale = v_scale
        from quoracle_tpu.models.quant import entry_nbytes
        self.nbytes = entry_nbytes(k, v, k_scale, v_scale)
        self.ts = time.monotonic()


class HostPageStore:
    """LRU-bounded host-RAM page store: hibernated sessions + stripped
    prefix blocks. Session entries DROP on budget pressure (they are one
    agent's private state — re-prefill recovers them); prefix blocks SPILL
    to disk first when a DiskPrefixStore is attached (they are shared,
    reconstructible state worth keeping cheap)."""

    def __init__(self, budget_bytes: int, model: str = ""):
        self.budget_bytes = int(budget_bytes)
        self.model = model
        self.sessions: OrderedDict[str, _HostSession] = OrderedDict()
        self.prefixes: OrderedDict[str, _HostBlock] = OrderedDict()
        self.bytes = 0
        self.evicted_sessions = 0
        self.evicted_prefixes = 0

    def _charge(self, n: int) -> None:
        self.bytes += n

    def put_session(self, key: str, entry: _HostSession,
                    spill_fn=None) -> None:
        old = self.sessions.pop(key, None)
        if old is not None:
            self.bytes -= old.nbytes
        self.sessions[key] = entry
        self._charge(entry.nbytes)
        self.shrink(spill_fn)

    def put_prefix(self, key: str, entry: _HostBlock,
                   spill_fn=None) -> None:
        if key in self.prefixes:
            return
        self.prefixes[key] = entry
        self._charge(entry.nbytes)
        self.shrink(spill_fn)

    def pop_session(self, key: str) -> Optional[_HostSession]:
        e = self.sessions.pop(key, None)
        if e is not None:
            self.bytes -= e.nbytes
        return e

    def get_prefix(self, key: str) -> Optional[_HostBlock]:
        e = self.prefixes.get(key)
        if e is not None:
            self.prefixes.move_to_end(key)
            e.ts = time.monotonic()
        return e

    def shrink(self, spill_fn=None) -> None:
        """Evict LRU entries until under budget. Prefix blocks go first
        (disk-spillable via ``spill_fn``; sessions are irreplaceable until
        their owner re-prefills), oldest-first within each kind."""
        from quoracle_tpu.infra.telemetry import KV_HOST_EVICTIONS_TOTAL
        while self.bytes > self.budget_bytes and self.prefixes:
            key, e = self.prefixes.popitem(last=False)
            self.bytes -= e.nbytes
            self.evicted_prefixes += 1
            KV_HOST_EVICTIONS_TOTAL.inc(model=self.model, kind="prefix")
            if spill_fn is not None:
                spill_fn(key, e)
        while self.bytes > self.budget_bytes and self.sessions:
            _, e = self.sessions.popitem(last=False)
            self.bytes -= e.nbytes
            self.evicted_sessions += 1
            KV_HOST_EVICTIONS_TOTAL.inc(model=self.model, kind="session")

    def headroom(self) -> int:
        return max(0, self.budget_bytes - self.bytes)

    def stats(self) -> dict:
        return {
            "budget_bytes": self.budget_bytes,
            "bytes": self.bytes,
            "sessions": len(self.sessions),
            "prefix_blocks": len(self.prefixes),
            "evicted_sessions": self.evicted_sessions,
            "evicted_prefixes": self.evicted_prefixes,
        }


class DiskPrefixStore:
    """Checksummed on-disk store of page-aligned prefix blocks, one file
    per block keyed by the content hash of the token prefix ending at the
    block. Files are ``.npz`` (tokens, k, v, crc) written atomically
    (tmp + rename — a torn write is an unreadable tmp file, never a
    half-entry) under ``<root>/<model-shape-signature>/``, so engines of
    different geometry or dtype can never load each other's bytes.

    ``load`` verifies the crc32 of the payload against the stored value
    and the requested token prefix against the stored one; any mismatch
    counts as corrupt, unlinks the file, and returns None — the caller
    falls back to a plain prefill. The store is an OPTIMIZATION with a
    paranoid boundary, never a correctness dependency.

    Bounded: ``budget_bytes`` (0 = unbounded) caps the directory —
    when a save overflows it, oldest-mtime entries unlink until the
    store fits again, and ``load`` touches an entry's mtime so pruning
    approximates LRU rather than FIFO. Directory size is tracked
    incrementally (one startup scan, refreshed at most every
    ``_SCAN_TTL_S``), so a /api/resources scrape costs no listdir."""

    _SCAN_TTL_S = 30.0

    def __init__(self, root: str, signature: str, model: str = "",
                 budget_bytes: int = 0):
        self.dir = os.path.join(root, signature)
        self.model = model
        self.budget_bytes = int(budget_bytes)
        os.makedirs(self.dir, exist_ok=True)
        self.writes = 0
        self.loads = 0
        self.corrupt = 0
        self.pruned = 0
        self._lock = named_lock("tier.disk")
        self._scan_entries = 0
        self._scan_bytes = 0
        self._scan_ts = 0.0
        with self._lock:
            self._rescan_locked()         # one startup scan; then cached

    def _rescan_locked(self) -> None:
        entries = nbytes = 0
        try:
            # TTL-bounded (30 s) accounting scan of this store's own
            # directory, under its own leaf lock — nothing on the
            # serving path contends for it during the walk.
            # qlint: allow[lock-blocking] TTL-bounded scan under the store's leaf lock
            for f in os.listdir(self.dir):
                if not f.endswith(".npz"):
                    continue
                entries += 1
                try:
                    nbytes += os.path.getsize(os.path.join(self.dir, f))
                except OSError:
                    pass
        except OSError:
            pass
        self._scan_entries, self._scan_bytes = entries, nbytes
        self._scan_ts = time.monotonic()

    def _prune_locked(self) -> None:
        """Unlink oldest-mtime entries until the store fits the budget
        (load() touches mtime, so eviction order approximates LRU)."""
        files = []
        try:
            # budget enforcement IS the lock's job: the prune must see a
            # stable ledger, and it only runs on the (async) spill
            # writer when a save overflows the byte budget.
            # qlint: allow[lock-blocking] budget prune on the spill writer, leaf lock
            for f in os.listdir(self.dir):
                if not f.endswith(".npz"):
                    continue
                p = os.path.join(self.dir, f)
                try:
                    stt = os.stat(p)
                except OSError:
                    continue
                files.append((stt.st_mtime, stt.st_size, p))
        except OSError:
            return
        files.sort()
        self._scan_entries = len(files)
        self._scan_bytes = sum(sz for _, sz, _ in files)
        self._scan_ts = time.monotonic()
        for _, sz, p in files:
            if self._scan_bytes <= self.budget_bytes:
                break
            try:
                os.unlink(p)
            except OSError:
                continue
            self._scan_bytes -= sz
            self._scan_entries -= 1
            self.pruned += 1

    @staticmethod
    def block_key(tokens: Sequence[int]) -> str:
        h = hashlib.sha256(
            np.asarray(tokens, np.int64).tobytes()).hexdigest()
        return h[:40]

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.npz")

    def has(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    @staticmethod
    def _crc(tokens: np.ndarray, k: np.ndarray, v: np.ndarray,
             k_scale: Optional[np.ndarray] = None,
             v_scale: Optional[np.ndarray] = None) -> int:
        c = zlib.crc32(tokens.tobytes())
        c = zlib.crc32(k.tobytes(), c)
        c = zlib.crc32(v.tobytes(), c)
        if k_scale is not None:
            # int8 entries (ISSUE 13): the per-page scale blocks live
            # under the SAME crc as the payload — a flipped scale byte
            # is indistinguishable from a flipped payload byte at this
            # boundary (reject, unlink, degrade to re-prefill)
            c = zlib.crc32(np.ascontiguousarray(k_scale).tobytes(), c)
            c = zlib.crc32(np.ascontiguousarray(v_scale).tobytes(), c)
        return c & 0xFFFFFFFF

    def save(self, key: str, tokens: Sequence[int], k: np.ndarray,
             v: np.ndarray, k_scale: Optional[np.ndarray] = None,
             v_scale: Optional[np.ndarray] = None) -> bool:
        """Write one block. The npz serialization and the tmp-file write
        run OUTSIDE ``_lock`` (qlint lock-blocking: the spill writer
        holding the lock through megabytes of compression would stall
        every stats()/load() accounting touch for the duration); only
        the atomic publish (rename) and the size accounting + budget
        prune run under it. Two writers racing the same content-
        addressed key both produce identical bytes under distinct tmp
        names, and the exists-check under the lock keeps the accounting
        single-counted."""
        path = self._path(key)
        if os.path.exists(path):
            return False                 # content-addressed: already there
        toks = np.asarray(tokens, np.int64)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                # KV payloads ship as RAW BYTES + dtype name + shape:
                # npz round-trips extension dtypes (ml_dtypes
                # bfloat16 — the serving cache dtype) as an opaque
                # void dtype, which would silently strip the dtype a
                # restore needs. Int8 entries (ISSUE 13) append their
                # per-page scale arrays under the same crc.
                extra = {}
                if k_scale is not None:
                    extra = {
                        "k_scale": np.ascontiguousarray(
                            k_scale, np.float32),
                        "v_scale": np.ascontiguousarray(
                            v_scale, np.float32),
                        "scale_shape": np.asarray(k_scale.shape),
                    }
                np.savez(
                    f, tokens=toks,
                    k=np.ascontiguousarray(k).view(np.uint8)
                    .reshape(-1),
                    v=np.ascontiguousarray(v).view(np.uint8)
                    .reshape(-1),
                    dtype=str(k.dtype), shape=np.asarray(k.shape),
                    crc=np.uint32(self._crc(toks, k, v, k_scale,
                                            v_scale)),
                    **extra)
            with self._lock:
                if os.path.exists(path):
                    # a concurrent writer published the same content
                    # first: drop ours, count nothing
                    os.unlink(tmp)
                    return False
                # atomic publish: one rename + one stat under the
                # store's own leaf lock keeps the size ledger exact; the
                # payload write already happened outside.
                # qlint: allow[lock-blocking] single rename, not payload I/O
                os.replace(tmp, path)
                self._scan_entries += 1
                try:
                    self._scan_bytes += os.path.getsize(path)
                except OSError:
                    pass                  # bytes drift; TTL heal below
                # TTL healing rescan moved OFF the scrape path (ISSUE
                # 16): stats() is a pure O(1) snapshot now (a 100k-
                # session replay scrapes /api/kv concurrently), so any
                # accounting drift heals here on the spill writer —
                # which is already doing disk I/O — at most once per
                # TTL window.
                if (time.monotonic() - self._scan_ts
                        > self._SCAN_TTL_S):
                    self._rescan_locked()
                if (self.budget_bytes
                        and self._scan_bytes > self.budget_bytes):
                    self._prune_locked()
            self.writes += 1
            return True
        except OSError:
            logger.exception("disk prefix write failed: %s", path)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def load(self, key: str,
             tokens: Sequence[int]) -> Optional[tuple[np.ndarray,
                                                      np.ndarray]]:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        # Chaos seam (ISSUE 11): a "corrupt" directive flips bytes in
        # the FILE before the normal load path runs, so the crc32
        # boundary below is what catches it — end-to-end proof that a
        # torn/rotted entry is skipped, unlinked, and never served.
        from quoracle_tpu.chaos.faults import CHAOS
        d = CHAOS.fire("kvtier.disk_load", model=self.model)
        if d is not None and d.kind == "corrupt":
            self._chaos_corrupt(path)
        # Chaos seam (ISSUE 13): "kvtier.scale_corrupt" flips a byte in
        # the TAIL of the entry file — where npz appends the int8
        # entry's per-page scale arrays — on the restore path. The crc
        # covers scales exactly like payload, so the SAME boundary must
        # reject it: a silently-wrong scale would dequantize every
        # token of the page to wrong values at temp 0.
        d = CHAOS.fire("kvtier.scale_corrupt", model=self.model)
        if d is not None and d.kind == "corrupt":
            self._chaos_corrupt(path, where=0.95)
        try:
            # Restore path by design (ARCHITECTURE §9): extend_prefix
            # calls this under the store lock so match→alloc→scatter→
            # insert stays atomic against concurrent alloc; the disk
            # read is the price of a restore and is tracked by
            # quoracle_kv_restore_ms. Sessioned callers already hold
            # the engine's paged lock, so no decode work is stalled
            # that wasn't already waiting on this restore.
            # qlint: allow[lock-blocking] restore reads under the store lock by design
            with np.load(path) as z:
                toks, crc = z["tokens"], int(z["crc"])
                dt = jax.numpy.dtype(str(z["dtype"]))
                shape = tuple(int(s) for s in z["shape"])
                k = z["k"].view(dt).reshape(shape)
                v = z["v"].view(dt).reshape(shape)
                ks = vs = None
                if "k_scale" in z.files:
                    sshape = tuple(int(s) for s in z["scale_shape"])
                    ks = np.asarray(z["k_scale"],
                                    np.float32).reshape(sshape)
                    vs = np.asarray(z["v_scale"],
                                    np.float32).reshape(sshape)
            if (self._crc(toks, k, v, ks, vs) != crc
                    or toks.tolist() != [int(t) for t in tokens]):
                raise ValueError("checksum/token mismatch")
            self.loads += 1
            try:
                # qlint: allow[lock-blocking] one-syscall LRU touch on the restore path
                os.utime(path)            # LRU touch for budget pruning
            except OSError:
                pass
            from quoracle_tpu.infra.telemetry import KV_DISK_LOADS_TOTAL
            KV_DISK_LOADS_TOTAL.inc(model=self.model, status="ok")
            return (k, v) if ks is None else (k, v, ks, vs)
        except Exception:                 # noqa: BLE001 — corrupt entry
            self.corrupt += 1
            logger.warning("corrupt disk prefix entry skipped: %s", path)
            from quoracle_tpu.infra.flightrec import FLIGHT
            from quoracle_tpu.infra.telemetry import KV_DISK_LOADS_TOTAL
            KV_DISK_LOADS_TOTAL.inc(model=self.model, status="corrupt")
            FLIGHT.record("kv_disk_corrupt", path=path, model=self.model)
            # exact incremental accounting (ISSUE 16): decrement the
            # ledger by the unlinked entry instead of invalidating the
            # whole scan — stats() never pays a rescan for a corrupt
            # eviction
            try:
                sz = os.path.getsize(path)
                os.unlink(path)
            except OSError:
                sz = -1
            if sz >= 0:
                with self._lock:
                    self._scan_entries = max(0, self._scan_entries - 1)
                    self._scan_bytes = max(0, self._scan_bytes - sz)
            return None

    @staticmethod
    def _chaos_corrupt(path: str, where: float = 0.5) -> None:
        """Flip a byte in place at fraction ``where`` of the file
        (chaos "corrupt" directives: 0.5 lands mid-payload;
        kvtier.scale_corrupt uses 0.95 to land in the appended scale
        arrays of an int8 entry). Best-effort: a vanished file is
        already the degraded case."""
        try:
            # qlint: allow[lock-blocking] chaos-only byte flip; armed plans never run on the production hot path
            with open(path, "r+b") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < 1:
                    return
                pos = min(size - 1, int(size * where))
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        except OSError:
            pass

    def stats(self) -> dict:
        # O(1) by contract (ISSUE 16): the entry/byte ledger is
        # maintained incrementally by save()/load()/prune, and the TTL
        # healing rescan runs on the save path — a scrape NEVER walks
        # the directory (tests/test_sim.py bounds this at 100k-entry
        # scale)
        with self._lock:
            return {"dir": self.dir, "entries": self._scan_entries,
                    "bytes": self._scan_bytes,
                    "budget_bytes": self.budget_bytes,
                    "writes": self.writes, "loads": self.loads,
                    "corrupt_skipped": self.corrupt,
                    "pruned": self.pruned}


class TierManager:
    """The tier ladder for one engine's SessionStore. Attached via
    ``GenerateEngine.attach_tier`` (which wires ``store.tier = self``);
    every method that touches the device pool assumes the engine's
    ``_paged_lock`` discipline described in the module docstring."""

    def __init__(self, store, model: str = "", host_mb: int = 256,
                 disk_dir: Optional[str] = None, paged_lock=None,
                 signature: Optional[str] = None,
                 disk_gb: float = 8.0):
        self.store = store
        self.model = model
        self.paged_lock = paged_lock
        self.signature = signature or (model.replace("/", "_")
                                       or "default")
        self.host = HostPageStore(int(host_mb) * (1 << 20), model=model)
        self.disk: Optional[DiskPrefixStore] = None
        if disk_dir:
            self.disk = DiskPrefixStore(
                disk_dir, self.signature, model=model,
                budget_bytes=int(disk_gb * (1 << 30)))
        # Fleet prefix service (ISSUE 12, serving/fabric/prefixd.py):
        # a read-through client attached via attach_prefixd — the
        # restore ladder's last rung (host → disk → FLEET) and the
        # spill writer's second publish target.
        self.prefixd = None
        # monotonic counters (stats() → /api/kv + bench config 14)
        self.demoted_sessions = 0
        self.demoted_prefix_pages = 0
        self.restored_sessions = 0
        self.restored_prefix_pages = 0
        self.restore_failures = 0
        self.spill_drops = 0
        # Disk spills are ASYNC: the eviction ladder runs inside
        # SessionStore.alloc with the store lock held (and the engine's
        # paged lock, for sessioned callers) — an npz write there would
        # stall every allocation under memory pressure. Only the
        # host-side numpy copy happens under the locks; writes queue to
        # a daemon writer thread. Best-effort by design: a full queue
        # drops the spill (the block is reconstructible by prefill).
        self._spill_q: Optional[queue.Queue] = None
        if self.disk is not None:
            self._ensure_spill_writer()

    def _ensure_spill_writer(self) -> None:
        if self._spill_q is None:
            self._spill_q = queue.Queue(maxsize=512)
            threading.Thread(
                target=self._spill_loop, daemon=True,
                name=f"kvtier-spill-{self.model or 'default'}").start()

    def attach_prefixd(self, client) -> None:
        """Wire the fleet prefix-service client (ISSUE 12): reads join
        extend_prefix's restore ladder, writes ride the async spill
        writer (wire I/O never happens under the serving locks)."""
        self.prefixd = client
        self._ensure_spill_writer()

    # -- device <-> host plumbing ---------------------------------------

    def _gather_host(self, pages: list[int]) -> tuple:
        """One device_get per victim: the pages' KV as host numpy —
        (k, v, k_scale, v_scale), scales None on unquantized pools.

        Deliberately under the store lock (ARCHITECTURE §9 demote
        invariant): eviction-as-demotion must copy the victim's pages
        before alloc's ladder releases them, or a concurrent writer
        could scribble the pool pages mid-copy. One victim per
        device_get bounds the stall; the async spill queue keeps DISK
        out of this window."""
        import jax
        st = self.store
        idx = np.asarray(pages, np.int32)
        # qlint: allow[hot-path-sync, lock-blocking] demote copies one victim under the store lock by design
        k = np.asarray(jax.device_get(st.k[:, idx]))
        # qlint: allow[hot-path-sync, lock-blocking] second half of the same bounded victim copy
        v = np.asarray(jax.device_get(st.v[:, idx]))
        if st.k_scale is None:
            return k, v, None, None
        # qlint: allow[hot-path-sync, lock-blocking] scale blocks ride the same bounded victim copy
        ks = np.asarray(jax.device_get(st.k_scale[:, idx]))
        # qlint: allow[hot-path-sync, lock-blocking] scale blocks ride the same bounded victim copy
        vs = np.asarray(jax.device_get(st.v_scale[:, idx]))
        return k, v, ks, vs

    def _scatter_device(self, pages: list[int], k: np.ndarray,
                        v: np.ndarray, k_scale=None,
                        v_scale=None) -> None:
        """Page-in via the pool scatter (shape-bucketed to bound
        compiles: the page-count axis pads to a power of two, padded
        slots target scratch page 0). Int8 pools scatter the scale
        blocks beside the payload pages."""
        import jax.numpy as jnp
        st = self.store
        n = len(pages)
        cap = _round_up_pow2(max(1, n))
        if cap != n:
            pad = ((0, 0), (0, cap - n), (0, 0), (0, 0), (0, 0))
            k = np.pad(k, pad)
            v = np.pad(v, pad)
            if k_scale is not None:
                spad = ((0, 0), (0, cap - n), (0, 0), (0, 0))
                k_scale = np.pad(k_scale, spad)
                v_scale = np.pad(v_scale, spad)
        idx = np.zeros((cap,), np.int32)
        idx[:n] = pages
        if st.k_scale is not None:
            if k_scale is None:
                # entry predates quantization (or scales were lost):
                # never scatter int8 payloads with stale scales — the
                # caller degrades to re-prefill
                raise ValueError(
                    "quantized pool restore without scale blocks")
            (st.k, st.v, st.k_scale,
             st.v_scale) = _scatter_pages_q(
                st.k, st.v, st.k_scale, st.v_scale, jnp.asarray(k),
                jnp.asarray(v), jnp.asarray(k_scale),
                jnp.asarray(v_scale), jnp.asarray(idx))
            return
        st.k, st.v = _scatter_pages(st.k, st.v, jnp.asarray(k),
                                    jnp.asarray(v), jnp.asarray(idx))

    # -- session hibernation --------------------------------------------

    def demote_session(self, key: str, sess) -> bool:
        """Copy a victim session's KV host-side before its pages release
        (called from SessionStore.alloc's ladder, both locks held). The
        caller still releases the pages — refcounted sharing is preserved
        because only the VICTIM's references drop; adopters and the radix
        tree keep the resident copies they already hold."""
        st = self.store
        pages = [p for p in sess.pages if p]
        if not pages or st.k is None:
            return False
        t0 = time.monotonic()
        try:
            k, v, ks, vs = self._gather_host(pages)
        except Exception:                 # noqa: BLE001 — demote is best-
            logger.exception("kv demote failed for %s", key)   # effort
            return False
        entry = _HostSession(list(sess.tokens), sess.start_pos, k, v,
                             ks, vs)
        self.host.put_session(key, entry,
                              spill_fn=self._spill_prefix_entry)
        self.demoted_sessions += 1
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import KV_DEMOTES_TOTAL
        KV_DEMOTES_TOTAL.inc(model=self.model, kind="session")
        self._note_bytes_saved("demote", entry)
        FLIGHT.record("kv_demote", model=self.model, what="session",
                      session=key, pages=len(pages),
                      ms=round((time.monotonic() - t0) * 1000, 2))
        return True

    def export_session(self, key: str) -> Optional[_HostSession]:
        """Page-export seam for cross-replica KV handoff (ISSUE 10,
        serving/handoff.py): hibernate the session OUT of this engine —
        exactly the eviction ladder's demote (one device_get, refcounted
        release, adopters/radix readers untouched) — and hand the host
        copy to the caller instead of parking it in this tier's store.
        A session already hibernated is handed over directly. Returns
        None when the session exists nowhere (caller re-prefills on the
        destination — always correct). Assumes the engine's paged lock
        is held, like every pool-touching method here."""
        st = self.store
        with st.lock:
            sess = st._sessions.get(key)
            if sess is None:
                return self.host.pop_session(key)
            if not self.demote_session(key, sess):
                return None
            del st._sessions[key]
            st._release(sess.pages)
            return self.host.pop_session(key)

    def adopt_session(self, key: str, entry: _HostSession) -> None:
        """Page-adopt seam (ISSUE 10): accept a handed-off host-side
        session copy into THIS tier's host store, after which the normal
        restore machinery (restore_session via prefetch or the engine's
        session lookup) pages it in — "hibernate on the prefill replica,
        restore on the decode replica". Replaces any stale copy under
        the same key; the live store is untouched (the caller drops or
        never had a resident session under this key)."""
        with self.store.lock:
            self.host.put_session(key, entry,
                                  spill_fn=self._spill_prefix_entry)

    def has_session(self, key: str) -> bool:
        return key in self.host.sessions

    def peek_tokens(self, key: str) -> Optional[list]:
        e = self.host.sessions.get(key)
        return list(e.tokens) if e is not None else None

    def discard_session(self, key: str) -> None:
        """The live store replaced or dropped this session — the host
        copy is stale and must never restore over fresher state."""
        self.host.pop_session(key)

    def restore_session(self, key: str):
        """Page a hibernated session back into the pool and re-register
        it. Returns the live session or None (pool unattainable / entry
        gone — the caller re-prefills, which is always correct). Assumes
        the engine's paged lock is held."""
        # Chaos seam (ISSUE 11): a "fail" directive exercises the
        # degrade-to-re-prefill path the docstring promises — the entry
        # stays in the host tier (a later touch may still restore it),
        # only THIS restore reports failure.
        from quoracle_tpu.chaos.faults import CHAOS
        d = CHAOS.fire("kvtier.restore", model=self.model)
        if d is not None and d.kind == "fail":
            self.restore_failures += 1
            return None
        st = self.store
        with st.lock:
            e = self.host.sessions.get(key)
            if e is None:
                return None
            n = e.k.shape[1]
            pages = st.alloc(n, protect=(key,))
            if pages is None:
                self.restore_failures += 1
                return None
            e = self.host.pop_session(key)
            if e is None:                 # raced a discard
                st._release(pages)
                return None
            t0 = time.monotonic()
            try:
                self._scatter_device(pages, e.k, e.v, e.k_scale,
                                     e.v_scale)
            except ValueError:
                # dtype/scale skew (a non-quantized entry adopted into a
                # quantized pool): degrade to re-prefill, never scatter
                # wrong bytes
                st._release(pages)
                self.restore_failures += 1
                return None
            sess = st.register_restored(key, list(e.tokens), pages,
                                        e.start_pos)
            self.restored_sessions += 1
            ms = (time.monotonic() - t0) * 1000
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import (
            KV_RESTORE_MS, KV_RESTORES_TOTAL,
        )
        KV_RESTORES_TOTAL.inc(model=self.model, kind="session",
                              source="host")
        KV_RESTORE_MS.observe(ms, model=self.model, kind="session")
        from quoracle_tpu.infra import costobs, introspect
        costobs.charge_restore(self.model, ms, source="host")
        # wait-state + heartbeat (ISSUE 18): the restore wall waits on
        # the DISPATCHING thread, so the batcher books it against the
        # step's rows; bytes feed the kv.restore liveness counter
        introspect.note_restore(ms, nbytes=int(e.k.nbytes)
                                + int(e.v.nbytes))
        FLIGHT.record("kv_restore", model=self.model, what="session",
                      session=key, pages=len(pages), ms=round(ms, 2))
        from quoracle_tpu.infra.telemetry import TRACER
        if TRACER.active():
            # the restore leg of a hibernated/handed-off session enters
            # the session's trace (ISSUE 15) — under the store lock's
            # caller, so a retroactive emit, never a bound span
            TRACER.emit("kv.restore", ms, ts=time.time() - ms / 1000.0,
                        session=key, model=self.model,
                        pages=len(pages))
        return sess

    # -- prefix-block tiering -------------------------------------------

    def _block_key(self, tokens: Sequence[int]) -> str:
        return DiskPrefixStore.block_key(tokens)

    def _spill_loop(self) -> None:
        while True:
            key, entry = self._spill_q.get()
            try:
                self._write_block(key, entry)
            except Exception:             # noqa: BLE001 — best-effort
                logger.exception("kv disk spill failed")
            finally:
                self._spill_q.task_done()

    def _note_bytes_saved(self, tier: str, entry) -> None:
        """Quantized byte-economy accounting (ISSUE 13): each tier move
        of an int8 entry counts the bf16-equivalent bytes it avoided
        holding/shipping (2·payload − (payload + scales)). No-op for
        unquantized entries."""
        if np.dtype(entry.k.dtype) != np.int8:
            return
        from quoracle_tpu.infra.telemetry import QUANT_BYTES_SAVED_TOTAL
        payload = int(entry.k.nbytes) + int(entry.v.nbytes)
        QUANT_BYTES_SAVED_TOTAL.inc(max(0, 2 * payload - entry.nbytes),
                                    model=self.model, tier=tier)

    def _write_block(self, key: str, entry: _HostBlock) -> None:
        """Writer-thread side of a spill: the actual (atomic, content-
        addressed) disk write — and, with a fleet prefix service
        attached, the publish to it — never under the store/paged
        locks."""
        if self.disk is not None \
                and self.disk.save(key, entry.tokens, entry.k, entry.v,
                                   entry.k_scale, entry.v_scale):
            from quoracle_tpu.infra.flightrec import FLIGHT
            from quoracle_tpu.infra.telemetry import KV_DISK_SPILLS_TOTAL
            KV_DISK_SPILLS_TOTAL.inc(model=self.model)
            FLIGHT.record("kv_disk_spill", model=self.model,
                          tokens=len(entry.tokens))
            self._note_bytes_saved("disk_spill", entry)
        if self.prefixd is not None:
            self.prefixd.publish(key, entry.tokens, entry.k, entry.v,
                                 entry.k_scale, entry.v_scale)

    def _enqueue_spill(self, key: str, entry: _HostBlock) -> None:
        if self._spill_q is None:
            return
        try:
            self._spill_q.put_nowait((key, entry))
        except queue.Full:
            self.spill_drops += 1

    def flush_spills(self) -> None:
        """Block until every queued disk write has landed (tests and
        orderly shutdown; the serving path never needs to wait)."""
        if self._spill_q is not None:
            self._spill_q.join()

    def _spill_prefix_entry(self, key: str, entry: _HostBlock) -> None:
        """Host-budget eviction of a prefix block: queue a disk spill
        when attached (dedup by content key at write time), else the
        block is simply gone. Runs under the store lock — must not
        touch the filesystem."""
        self._enqueue_spill(key, entry)

    def capture_leaf(self, tokens: Sequence[int], page: int) -> None:
        """A radix-cache leaf is about to be stripped (prefix_cache.evict):
        keep its block alive in the host tier instead of recomputing it
        later. Called under the store lock (and the paged lock, via
        alloc)."""
        st = self.store
        if st.k is None:
            return
        key = self._block_key(tokens)
        if key in self.host.prefixes:
            return
        if self.disk is not None and self.disk.has(key):
            return        # already durable; skip the device_get
        try:
            k, v, ks, vs = self._gather_host([page])
        except Exception:                 # noqa: BLE001 — best-effort
            logger.exception("prefix leaf capture failed")
            return
        self.host.put_prefix(
            key, _HostBlock(list(tokens), k[:, 0], v[:, 0],
                            None if ks is None else ks[:, 0],
                            None if vs is None else vs[:, 0]),
            spill_fn=self._spill_prefix_entry)
        self.demoted_prefix_pages += 1
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import KV_DEMOTES_TOTAL
        KV_DEMOTES_TOTAL.inc(model=self.model, kind="prefix")
        FLIGHT.record("kv_demote", model=self.model, what="prefix",
                      tokens=len(tokens))

    def persist_block(self, tokens: Sequence[int], page: int) -> None:
        """Insert-time disk persistence: a block newly cached in the
        radix tree is written through to disk (content-addressed — a
        block already persisted costs one stat()). This is what makes a
        restarted process warm: the disk store accumulates the fleet's
        hot prefixes while they are still hot, not only at eviction.
        Only the device→host copy happens here (the caller holds the
        store lock, so the page content is stable); the npz write rides
        the spill queue."""
        if self.disk is None and self.prefixd is None:
            return
        key = self._block_key(tokens)
        if self.disk is not None and self.disk.has(key):
            return
        st = self.store
        if st.k is None:
            return
        try:
            k, v, ks, vs = self._gather_host([page])
        except Exception:                 # noqa: BLE001 — best-effort
            return
        self._enqueue_spill(
            key, _HostBlock([int(t) for t in tokens], k[:, 0], v[:, 0],
                            None if ks is None else ks[:, 0],
                            None if vs is None else vs[:, 0]))

    def extend_prefix(self, tokens: Sequence[int], cap: int) -> int:
        """Lazily page tiered prefix blocks back into the radix tree:
        while the tree's page-aligned match of ``tokens`` can be extended
        by a block held in the host or disk tier, alloc a page, scatter
        the block in, and insert it. Returns blocks restored. Called from
        SessionStore.match_prefix under the store lock (paged lock held
        by the sessioned caller)."""
        st = self.store
        if st.k is None:
            return 0
        page = st.page
        restored = 0
        attempted: set = set()
        shrinks = 0
        while True:
            j = st.prefix_cache.match_len(tokens, cap) // page
            end = (j + 1) * page
            if end > min(len(tokens), cap):
                break
            prefix = [int(t) for t in tokens[:end]]
            key = self._block_key(prefix)
            if key in attempted:
                break                     # do not thrash a tiny pool
            attempted.add(key)
            blk = self.host.get_prefix(key)
            source = "host"
            if blk is None and self.disk is not None:
                loaded = self.disk.load(key, prefix)
                if loaded is not None:
                    blk = _HostBlock(prefix, *loaded)
                    source = "disk"
            if blk is None and self.prefixd is not None:
                # The fleet rung (ISSUE 12): same restore-path-by-design
                # argument as the disk read above — sessioned callers
                # already hold the paged lock waiting on this restore,
                # and the fetch degrades to a miss on any failure.
                # qlint: allow[lock-blocking] fleet prefix fetch on the restore path by design
                fetched = self.prefixd.fetch(key, prefix)
                if fetched is not None:
                    blk = _HostBlock(prefix, *fetched)
                    source = "prefixd"
            if blk is None:
                break
            pages = st.alloc(1)
            if pages is None:
                break
            path = st.prefix_cache._walk(tokens, cap)
            if len(path) != j:
                # alloc's eviction ladder strips radix leaves first and
                # match_len bumps no LRU stamps, so it can take the
                # deepest node of the very path just matched. Inserting
                # at depth j would then label this block's KV with block
                # j-1's tokens and serve wrong bytes at temp 0. Release
                # and restart from a fresh match (bounded: a pool too
                # small to hold the chain oscillates, so give up after a
                # few shrinks instead of thrashing).
                st._release(pages)
                attempted.discard(key)
                shrinks += 1
                if shrinks > 8:
                    break
                continue
            t0 = time.monotonic()
            try:
                self._scatter_device(
                    pages, blk.k[:, None], blk.v[:, None],
                    None if blk.k_scale is None else blk.k_scale[:, None],
                    None if blk.v_scale is None else blk.v_scale[:, None])
            except ValueError:
                # scale-less block against a quantized pool (signature
                # dirs make this near-impossible; stay paranoid anyway)
                st._release(pages)
                self.restore_failures += 1
                break
            added = st.prefix_cache.insert(
                prefix, [nd.page for nd in path] + pages)
            if not added:
                st._release(pages)        # raced an insert; keep theirs
                continue
            # Drop alloc's base reference: the tree's reference must be
            # the ONLY holder of a restored block (store-back reaches
            # the same state when the inserting session later drops).
            # Keeping the base ref pins the page at refcount 2 forever —
            # _evictable_leaf needs exactly 1 — and a restart-warmed
            # process would steadily lose pool capacity.
            st._release(pages)
            restored += 1
            self.restored_prefix_pages += 1
            ms = (time.monotonic() - t0) * 1000
            from quoracle_tpu.infra.telemetry import (
                KV_RESTORE_MS, KV_RESTORES_TOTAL,
            )
            KV_RESTORES_TOTAL.inc(model=self.model, kind="prefix",
                                  source=source)
            KV_RESTORE_MS.observe(ms, model=self.model, kind="prefix")
            from quoracle_tpu.infra import costobs, introspect
            costobs.charge_restore(self.model, ms, source=source)
            introspect.note_restore(ms, nbytes=int(blk.k.nbytes)
                                    + int(blk.v.nbytes))
        if restored:
            from quoracle_tpu.infra.flightrec import FLIGHT
            FLIGHT.record("kv_restore", model=self.model, what="prefix",
                          blocks=restored)
        return restored

    # -- reads -----------------------------------------------------------

    def demotable_bytes(self, page_bytes: int) -> int:
        """How many HBM bytes could move to the host tier right now
        without losing state. Exact, not optimistic: reuses alloc's
        attainability accounting over every resident session — victim-
        exclusive pages plus cache leaves that would strip once the
        victims' references drop. Pages held by in-flight adopters
        (acquire() without a registered session) stay resident and are
        NOT counted, so the QoS admission controller
        (serving/admission.py) never sees headroom the eviction ladder
        cannot deliver. Bounded by the host budget's remaining
        headroom."""
        st = self.store
        with st.lock:
            reclaimable = (st._attainable(list(st._sessions))
                           - len(st._free))
        return min(max(0, reclaimable) * page_bytes,
                   self.host.headroom())

    def stats(self) -> dict:
        return {
            "model": self.model,
            "host": self.host.stats(),
            "disk": self.disk.stats() if self.disk is not None else None,
            "demoted_sessions": self.demoted_sessions,
            "demoted_prefix_pages": self.demoted_prefix_pages,
            "restored_sessions": self.restored_sessions,
            "restored_prefix_pages": self.restored_prefix_pages,
            "restore_failures": self.restore_failures,
            "spill_queue": (self._spill_q.qsize()
                            if self._spill_q is not None else 0),
            "spill_drops": self.spill_drops,
            "prefixd": (self.prefixd.stats()
                        if self.prefixd is not None else None),
        }
