"""Per-class latency SLOs with EWMA tail tracking (ISSUE 4 tentpole,
part c).

The control loop: every retired row reports (class, latency) here; the
tracker keeps an exponentially-weighted mean and variance per class and
derives a TAIL estimate (mean + 2σ — a p95-flavored proxy that needs no
window buffer and reacts within ~1/alpha observations). While the
INTERACTIVE tail sits over its target, BATCH and BACKGROUND admission
weight is DEMOTED (multiplied by ``demote_to``) in the weighted-fair
queue — interactive latency recovers by slowing bulk work down, not by
dropping it. The demotion releases with hysteresis (tail back under
``recover_ratio × target``) so the weights don't flap at the boundary.

Every demote/restore lands in the flight recorder (``qos_demote`` /
``qos_restore``) and the ``quoracle_qos_demotions_total`` counter; the
live tail estimates and multipliers are gauges, so a scrape shows both
the burn and the response.
"""

from __future__ import annotations

import math
import threading
from typing import Optional

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.infra.telemetry import (
    QOS_CLASS_TAIL_MS, QOS_DEMOTIONS_TOTAL, QOS_WEIGHT_MULTIPLIER,
)
from quoracle_tpu.serving.qos import Priority, coerce_priority

# Default per-class tail targets (ms): a human notices ~1.5 s; agent
# turns tolerate a few seconds; bulk classes only alert, never demote.
DEFAULT_TARGETS_MS: dict[Priority, float] = {
    Priority.INTERACTIVE: 1500.0,
    Priority.AGENT: 6000.0,
    Priority.BATCH: 30000.0,
    Priority.BACKGROUND: 120000.0,
}


class SLOTracker:
    """EWMA tail tracker + the INTERACTIVE-burn → BATCH-demotion loop.

    Thread-safe; ``observe`` is the hot path (a few float ops under one
    lock). ``weight_multiplier`` is read by WeightedFairPolicy at every
    DRR credit refill, so a demotion shapes the very next admit.
    """

    def __init__(self, targets_ms: Optional[dict] = None,
                 alpha: float = 0.15, demote_to: float = 0.25,
                 recover_ratio: float = 0.8):
        base = dict(DEFAULT_TARGETS_MS)
        for k, v in (targets_ms or {}).items():
            base[coerce_priority(k)] = float(v)
        self.targets_ms = base
        self.alpha = float(alpha)
        self.demote_to = float(demote_to)
        self.recover_ratio = float(recover_ratio)
        self._mean: dict[Priority, float] = {}
        self._var: dict[Priority, float] = {}
        self._count: dict[Priority, int] = {p: 0 for p in Priority}
        self._demoted = False
        self.demotions = 0
        self._lock = named_lock("qos.slo")
        for p in Priority:
            QOS_WEIGHT_MULTIPLIER.set(1.0, cls=p.name.lower())

    # ------------------------------------------------------------------

    def observe(self, priority, latency_ms: float) -> None:
        cls = coerce_priority(priority)
        a = self.alpha
        with self._lock:
            m = self._mean.get(cls)
            if m is None:
                self._mean[cls] = float(latency_ms)
                self._var[cls] = 0.0
            else:
                d = float(latency_ms) - m
                self._mean[cls] = m + a * d
                # EW variance (West 1979 form): decays like the mean
                self._var[cls] = (1 - a) * (self._var[cls] + a * d * d)
            self._count[cls] += 1
            tail = self._tail_locked(cls)
            flipped = self._update_demotion_locked()
        QOS_CLASS_TAIL_MS.set(round(tail, 2), cls=cls.name.lower())
        if flipped is not None:
            self._record_flip(flipped)

    def _tail_locked(self, cls: Priority) -> float:
        m = self._mean.get(cls)
        if m is None:
            return 0.0
        return m + 2.0 * math.sqrt(max(0.0, self._var.get(cls, 0.0)))

    def _update_demotion_locked(self) -> Optional[bool]:
        """Returns True on demote, False on restore, None on no change.
        Demotion needs a few observations first — one slow warmup row
        must not throttle the whole batch tier."""
        tail = self._tail_locked(Priority.INTERACTIVE)
        target = self.targets_ms[Priority.INTERACTIVE]
        if (not self._demoted and tail > target
                and self._count[Priority.INTERACTIVE] >= 3):
            self._demoted = True
            self.demotions += 1
            return True
        if self._demoted and tail < self.recover_ratio * target:
            self._demoted = False
            return False
        return None

    def _record_flip(self, demoted: bool) -> None:
        from quoracle_tpu.infra.flightrec import FLIGHT
        tail = self.tail_ms(Priority.INTERACTIVE)
        if demoted:
            QOS_DEMOTIONS_TOTAL.inc()
            FLIGHT.record("qos_demote",
                          interactive_tail_ms=round(tail, 1),
                          target_ms=self.targets_ms[Priority.INTERACTIVE],
                          demote_to=self.demote_to)
        else:
            FLIGHT.record("qos_restore",
                          interactive_tail_ms=round(tail, 1))
        for p in (Priority.BATCH, Priority.BACKGROUND):
            QOS_WEIGHT_MULTIPLIER.set(
                self.demote_to if demoted else 1.0, cls=p.name.lower())

    # -- reads -----------------------------------------------------------

    def weight_multiplier(self, priority) -> float:
        cls = coerce_priority(priority)
        with self._lock:
            if self._demoted and cls >= Priority.BATCH:
                return self.demote_to
            return 1.0

    def tail_ms(self, priority) -> float:
        cls = coerce_priority(priority)
        with self._lock:
            return self._tail_locked(cls)

    @property
    def demoted(self) -> bool:
        with self._lock:
            return self._demoted

    def burn(self, priority=Priority.INTERACTIVE) -> float:
        """Tail-over-target ratio for a class (ISSUE 14): 0.0 with no
        observations, 1.0 exactly at target, >1.0 while the SLO burns.
        The fleet controller reads this as its scale-up pressure signal
        — the same number the demotion loop compares against 1.0."""
        cls = coerce_priority(priority)
        with self._lock:
            if not self._count.get(cls):
                return 0.0
            return self._tail_locked(cls) / max(1e-9,
                                                self.targets_ms[cls])

    def stats(self) -> dict:
        with self._lock:
            return {
                "demoted": self._demoted,
                "demotions": self.demotions,
                "demote_to": self.demote_to,
                "classes": {
                    p.name.lower(): {
                        "target_ms": self.targets_ms[p],
                        "tail_ms": round(self._tail_locked(p), 2),
                        "mean_ms": round(self._mean.get(p, 0.0), 2),
                        "observed": self._count[p],
                    } for p in Priority
                },
            }
