"""Disaggregated multi-replica serving plane (ISSUE 10 tentpole).

Before this module one ``Runtime`` owned one ``TPUBackend`` owned one
mesh: scale meant re-architecting. A :class:`ClusterPlane` is a
``ModelBackend`` over N REPLICAS — each replica a full per-member engine
set (a ``TPUBackend``) on its own slice of the device partition,
role-tagged into tiers:

  * **prefill** replicas — MFU-optimized: chunked ragged prefill only
    (engines carry ``role='prefill'``, which hard-caps generates at one
    emitted token — the first-token semantics of disaggregated serving);
    no continuous batcher, no draft models.
  * **decode** replicas — HBM-bandwidth-optimized: continuous batching
    plus speculation, exactly the single-Runtime production decode path.
  * **unified** replicas — the non-disaggregated data-parallel mode
    (``--replicas N`` without ``--disaggregate``): whole requests,
    routed by affinity + load.

The request flow in disaggregated mode ("hibernate on the prefill
replica, restore on the decode replica" — PR 7's machinery, split
across engines by serving/handoff.py):

  1. the ROUTER (serving/router.py) places the row: session affinity
     first (decode rows stick to the replica holding their pages), then
     the least-loaded eligible replica by the admission controller's
     own sampled signals;
  2. the prefill replica's engine prefills the prompt and emits ONE
     token (``max_new_tokens=1``), storing the prompt KV in its pages;
  3. the handoff broker hibernates that session into an envelope
     (signature-checked) and the decode replica adopts it by page-in;
  4. the decode replica decodes the continuation (prompt + first token)
     through its continuous batcher — resuming the restored session, so
     nothing re-prefills — and the plane assembles one result from both
     phases. Per-token bits are IDENTICAL to a monolithic Runtime at
     temperature 0 (greedy, constrained-JSON, and speculative — tier-1
     asserted): the chunked-continuation equality the scheduler already
     guarantees, plus the restore bit-equality the tier already
     guarantees, compose into the cluster's acceptance invariant.

Every single-process invariant becomes a per-replica invariant (one
batcher, one admission controller, one page pool PER REPLICA) plus this
routing layer; the conversion changes no output bits.

Degraded modes (tier-1 tested): a decode replica dying mid-row is
re-placed through its retained handoff envelope onto a surviving decode
replica (or failed with a structured error naming the replica — never
silently lost); a version-signature mismatch at handoff degrades to a
cold re-prefill on the decode side; when every decode replica sheds,
the front door sheds with the MAX retry-after (the 429 contract).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional, Sequence

import numpy as np

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.chaos.faults import CHAOS
from quoracle_tpu.infra import fleetobs
from quoracle_tpu.infra.telemetry import (
    CLUSTER_REPLICAS, CLUSTER_REQUESTS_TOTAL, TRACER,
)
from quoracle_tpu.models.runtime import (
    ModelBackend, QueryRequest, QueryResult, TPUBackend, Usage,
)
from quoracle_tpu.serving.admission import AdmissionError
from quoracle_tpu.serving.handoff import HandoffError, KVHandoff
from quoracle_tpu.serving.router import ClusterRouter

logger = logging.getLogger(__name__)


class ReplicaFailedError(RuntimeError):
    """A row's serving replica died and no surviving replica could take
    it over. Structured: the web/consensus layers surface replica id +
    phase instead of a bare traceback — a lost replica must read as an
    incident, never as a silently dropped row."""

    def __init__(self, message: str, replica_id: str = "",
                 phase: str = "decode"):
        super().__init__(message)
        self.replica_id = replica_id
        self.phase = phase


@dataclasses.dataclass
class Replica:
    """One role-tagged engine tier member."""

    replica_id: str
    role: str                    # "prefill" | "decode" | "unified"
    backend: TPUBackend
    alive: bool = True

    def close(self) -> None:
        self.backend.close()


class RemoteReplica:
    """A replica that is a NETWORK PEER (ISSUE 12, serving/fabric/):
    the same replica interface — ``replica_id`` / ``role`` / ``alive``
    / ``backend`` — over a fabric transport to a FabricPeer process,
    so the ClusterRouter's placement, affinity, liveness, and
    aggregate-admission logic run unchanged whether a replica lives in
    this process or on another host. ``backend`` is a thin facade:
    ``query`` delegates whole requests over the wire (the unified /
    affinity / failover paths), ``qos_controller`` is the
    SignalSnapshot poll proxy the router scores and admits through.
    The split prefill→handoff→decode flow rides the dedicated
    ``prefill``/``adopt_decode`` ops (fabric/frontdoor.FabricPlane
    drives those)."""

    def __init__(self, transport, replica_id: Optional[str] = None,
                 role: Optional[str] = None):
        from quoracle_tpu.serving.fabric import wire
        from quoracle_tpu.serving.fabric.frontdoor import (
            RemoteSignalsProxy,
        )
        self.transport = transport
        _, payload = transport.request(wire.MSG_HELLO,
                                       wire.encode_json({}))
        hello = wire.decode_json(payload)
        self.replica_id = replica_id or hello.get("replica_id", "peer")
        self.role = role or hello.get("role", "unified")
        self.pool = list(hello.get("pool") or ())
        self.signatures = dict(hello.get("signatures") or {})
        self.alive = True
        self._signals = RemoteSignalsProxy(transport)
        self.backend = _RemoteBackendFacade(self)

    # -- wire ops ---------------------------------------------------------

    @staticmethod
    def _trace_dict() -> Optional[dict]:
        """The calling thread's trace context as a wire-able dict —
        stamped onto every peer-bound payload so the peer's spans land
        in the caller's trace (ISSUE 15)."""
        ctx = fleetobs.TraceContext.current()
        return ctx.to_dict() if ctx is not None else None

    @staticmethod
    def _tree_dict() -> Optional[dict]:
        """The calling thread's tree context as a wire-able dict —
        lineage for peers whose charges must land on the caller's
        tree node (ISSUE 20)."""
        from quoracle_tpu.infra import treeobs
        if not treeobs.enabled():
            return None
        ctx = treeobs.current()
        return ctx.to_dict() if ctx is not None else None

    def serve(self, request):
        from quoracle_tpu.serving.fabric import wire
        d = wire.request_to_dict(request)
        if d.get("trace") is None:
            d["trace"] = self._trace_dict()
        if d.get("tree") is None:
            d["tree"] = self._tree_dict()
        _, payload = self.transport.request(
            wire.MSG_SERVE, wire.encode_json(d))
        return wire.result_from_dict(wire.decode_json(payload))

    def prefill(self, request, handoff_id: str) -> tuple[dict, bytes]:
        """The prefill phase on this peer: returns (meta, envelope
        bytes) — or (meta-with-"result", b"") for rows that never
        dispatched (overflow / deadline)."""
        from quoracle_tpu.serving.fabric import wire
        d = wire.request_to_dict(request)
        if d.get("trace") is None:
            d["trace"] = self._trace_dict()
        if d.get("tree") is None:
            d["tree"] = self._tree_dict()
        _, payload = self.transport.request(
            wire.MSG_PREFILL,
            wire.encode_json({
                "request": d,
                "handoff_id": handoff_id}))
        meta, body = wire.unpack_blob(payload)
        return meta, bytes(body)

    def adopt_decode(self, meta: dict, env_bytes: bytes,
                     owns: bool = False) -> dict:
        """Ship the retained envelope bytes + row metadata; the peer
        gates on its own kv_signature BEFORE parsing a page byte,
        adopts, and decodes the continuation through its production
        batcher."""
        from quoracle_tpu.serving.fabric import wire
        header = {"handoff_id": meta["handoff_id"],
                  "model_spec": meta["model_spec"],
                  "prompt": meta["prompt"], "row": meta["row"],
                  "g1": meta["g1"], "owns": owns,
                  "trace": self._trace_dict(),
                  "tree": self._tree_dict()}
        _, payload = self.transport.request(
            wire.MSG_DECODE, wire.pack_blob(header, env_bytes))
        return wire.decode_json(payload)

    def pull_spans(self, session_id: Optional[str] = None,
                   trace_id: Optional[str] = None) -> list[dict]:
        """This peer's span-ring slice for one session/trace — the new
        wire op the front door's timeline assembly pulls (ISSUE 15)."""
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_OBS, wire.encode_json({
                "op": "spans", "session_id": session_id,
                "trace_id": trace_id}))
        out = wire.decode_json(payload)
        return list(out.get("spans") or ())

    def pull_tree(self, tree_id: str) -> dict:
        """This peer's local tree-registry state for one tree — the
        MSG_OBS ``tree`` op the front door's /api/tree assembly pulls
        (ISSUE 20). The payload is registry-tagged so the merge counts
        loopback peers (shared process registry) exactly once."""
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_OBS, wire.encode_json({
                "op": "tree", "tree_id": tree_id}))
        return wire.decode_json(payload)

    def obs_metrics(self) -> dict:
        """This peer's lossless metrics state (MetricsRegistry.
        export_state + rollup scalars) — the federation scrape input."""
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_OBS, wire.encode_json({"op": "metrics"}))
        return wire.decode_json(payload)

    def obs_incident(self, incident_id: str, reason: str = "") -> dict:
        """Ask this peer to dump its flight ring into the named
        incident bundle — the correlated-capture broadcast leg."""
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_OBS, wire.encode_json({
                "op": "incident", "incident_id": incident_id,
                "reason": reason}))
        return wire.decode_json(payload)

    def obs_profile(self) -> dict:
        """This peer's liveness/hotspot state (ISSUE 18): profiler
        windows, heartbeats, stall status, wait totals — the front
        door's fleet-scope /api/profile pull."""
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_OBS, wire.encode_json({"op": "profile"}))
        return wire.decode_json(payload)

    def session_resident(self, request) -> bool:
        """Affinity guard: does the peer still hold this session (LRU
        churn can outlive the affinity entry)? Unreachable peers answer
        False — fresh placement handles them."""
        from quoracle_tpu.serving.fabric import wire
        if not request.session_id:
            return False
        try:
            _, payload = self.transport.request(
                wire.MSG_META,
                wire.encode_json({"op": "session_resident",
                                  "model_spec": request.model_spec,
                                  "session_id": request.session_id}))
        except wire.WireError:
            return False
        return bool(wire.decode_json(payload).get("value"))

    def drop_session(self, session_id: str) -> None:
        from quoracle_tpu.serving.fabric import wire
        self.transport.request(
            wire.MSG_DROP_SESSION,
            wire.encode_json({"session_id": session_id}))

    def meta(self, op: str, **kw):
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_META, wire.encode_json({"op": op, **kw}))
        return wire.decode_json(payload).get("value")

    def embed(self, texts):
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(
            wire.MSG_EMBED, wire.encode_json({"texts": list(texts)}))
        header, body = wire.unpack_blob(payload)
        arr = wire._array_from(body, wire._np_dtype(header["dtype"]),
                               tuple(header["shape"]))
        return np.copy(arr)

    def stats(self) -> dict:
        from quoracle_tpu.serving.fabric import wire
        _, payload = self.transport.request(wire.MSG_STATS,
                                            wire.encode_json({}))
        return wire.decode_json(payload)

    def close(self) -> None:
        self.transport.close()


class _RemoteBackendFacade:
    """Just enough ``backend`` surface for the router (signals, stats),
    ClusterPlane._delegate (query), and the resource layer (an empty
    ``engines`` map — a remote peer attributes its own HBM)."""

    def __init__(self, replica: RemoteReplica):
        self._replica = replica
        self.pool = list(replica.pool)
        self.engines: dict = {}

    @property
    def qos_controller(self):
        return self._replica._signals

    def query(self, requests):
        return [self._replica.serve(r) for r in requests]

    def scheduler_stats(self) -> dict:
        try:
            return self._replica.stats().get("scheduler", {})
        except Exception:                 # noqa: BLE001 — silent peer
            return {}

    def close(self) -> None:
        self._replica.close()


class ClusterPlane(ModelBackend):
    """N replicas + a router + a handoff broker behind the ModelBackend
    seam — the consensus/agent layers cannot tell it from a single
    TPUBackend, which is the point."""

    def __init__(self, replicas: Sequence[Replica],
                 router: Optional[ClusterRouter] = None,
                 handoff: Optional[KVHandoff] = None):
        if not replicas:
            raise ValueError("a cluster needs at least one replica")
        self.replicas: list[Replica] = list(replicas)
        self.router = router or ClusterRouter()
        self.handoff = handoff or KVHandoff()
        for rep in self.replicas:
            self.router.register(rep)
        self.disaggregated = any(r.role == "prefill"
                                 for r in self.replicas)
        if self.disaggregated and not any(r.role == "decode"
                                          for r in self.replicas):
            raise ValueError("disaggregated cluster has prefill "
                             "replicas but no decode replica")
        self.pool = list(self.replicas[0].backend.pool)
        self._bus = None
        self._lock = named_lock("cluster.plane")
        self._seq = 0
        # Elastic fleet (ISSUE 14): ``build`` saves its backend kwargs
        # here so ``add_replica`` can construct new replicas in either
        # role; a directly-constructed plane can set it explicitly (the
        # fleet tests inject tiny-engine factories).
        self._replica_args: Optional[dict] = None
        self._embedder = None
        # monotonic replica-id counter: ids must never be reused after
        # a retirement — a stale affinity or flight event naming a
        # retired id must stay unambiguous forever
        self._rep_seq = len(self.replicas)
        # fleet observability (ISSUE 15): any serving plane can answer
        # a timeline pull, so the span ring captures from build time
        fleetobs.ensure_ring()
        self._refresh_replica_gauges()

    # -- construction ----------------------------------------------------

    @classmethod
    def build(cls, pool: Sequence[str], *, replicas: int = 2,
              disaggregate: bool = True, seed: int = 0,
              submeshes_by_replica: Optional[Sequence] = None,
              qos=None, draft_map: Optional[dict] = None,
              draft_k: int = 6, continuous: bool = True,
              continuous_chunk: int = 32, continuous_slots: int = 8,
              host_kv_mb: int = 0, disk_kv_dir: Optional[str] = None,
              disk_kv_gb: float = 8.0, embed_model: Optional[str] = None,
              quantize_weights: bool = False,
              quantize_kv: bool = False) -> "ClusterPlane":
        """Build N replicas over one model pool. With ``disaggregate``,
        the first ``max(1, replicas // 2)`` replicas become the prefill
        tier and the rest the decode tier (decode-heavy by default —
        agent workloads are decode-bound); otherwise every replica is
        unified. The embedder is built once and shared (embedding is
        stateless — replicating it would waste a full encoder's HBM per
        replica). Handoff requires KV tiers on both sides, so a
        disaggregated build defaults ``host_kv_mb`` to 256 when unset;
        a shared ``disk_kv_dir`` makes the signature dir the
        cross-replica prefix medium (replicas warm-start from each
        other's persisted blocks)."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if disaggregate and replicas < 2:
            raise ValueError("--disaggregate needs --replicas >= 2 "
                             "(one prefill + one decode tier minimum)")
        if disaggregate and not host_kv_mb:
            host_kv_mb = 256            # the handoff transport medium
        n_prefill = max(1, replicas // 2) if disaggregate else 0
        reps: list[Replica] = []
        embedder = None
        for i in range(replicas):
            role = ("prefill" if i < n_prefill else "decode") \
                if disaggregate else "unified"
            mesh = (submeshes_by_replica[i]
                    if submeshes_by_replica is not None else None)
            prefill = role == "prefill"
            backend = TPUBackend(
                pool, seed=seed, embed_model=embed_model,
                embedder=embedder, submeshes=mesh,
                # prefill tier: no decode loop, no drafts — one ragged
                # prefill call per placement is its whole job
                continuous=continuous and not prefill,
                continuous_chunk=continuous_chunk,
                continuous_slots=continuous_slots,
                draft_map=None if prefill else draft_map,
                draft_k=draft_k, qos=qos,
                host_kv_mb=host_kv_mb, disk_kv_dir=disk_kv_dir,
                disk_kv_gb=disk_kv_gb,
                # quantization is uniform across the cluster: a
                # mixed-precision replica pair would reject every
                # handoff at the signature gate (by design — see
                # kv_signature), so the plane builds one regime
                quantize_weights=quantize_weights,
                quantize_kv=quantize_kv)
            if embedder is None:
                embedder = backend.embedder
            if prefill:
                for spec in pool:
                    backend.engines[spec].role = "prefill"
            elif disaggregate:
                for spec in pool:
                    backend.engines[spec].role = "decode"
            reps.append(Replica(replica_id=f"{role}-{i}", role=role,
                                backend=backend))
        plane = cls(reps)
        # the fleet controller's scale-up factory: same pool, same QoS,
        # same quantization regime — new replicas land on the default
        # device set (per-replica submesh partitions are a boot-time
        # layout; an elastically added replica shares devices until the
        # next reboot repartitions)
        plane._replica_args = dict(
            pool=list(pool), seed=seed, embed_model=embed_model,
            qos=qos, draft_map=draft_map,
            draft_k=draft_k, continuous=continuous,
            continuous_chunk=continuous_chunk,
            continuous_slots=continuous_slots, host_kv_mb=host_kv_mb,
            disk_kv_dir=disk_kv_dir, disk_kv_gb=disk_kv_gb,
            quantize_weights=quantize_weights, quantize_kv=quantize_kv)
        plane._embedder = embedder
        return plane

    def close(self) -> None:
        for rep in self.replicas:
            try:
                rep.close()
            except Exception:             # noqa: BLE001 — best-effort
                logger.exception("replica %s close failed",
                                 rep.replica_id)

    def _refresh_replica_gauges(self) -> None:
        counts: dict[tuple, int] = {}
        for rep in self.replicas:
            key = (rep.role, "alive" if rep.alive else "dead")
            counts[key] = counts.get(key, 0) + 1
        for role in ("prefill", "decode", "unified"):
            for liveness in ("alive", "dead"):
                CLUSTER_REPLICAS.set(counts.get((role, liveness), 0),
                                     role=role, liveness=liveness)

    def _own_session_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"__cluster{self._seq}"

    def _broadcast(self, event: dict) -> None:
        if self._bus is None:
            return
        try:
            from quoracle_tpu.infra.bus import TOPIC_CLUSTER
            self._bus.broadcast(TOPIC_CLUSTER,
                                {"ts": time.time(), **event})
        except Exception:                 # noqa: BLE001 — telemetry only
            logger.exception("cluster broadcast failed")

    def _mark_failed(self, rep: Replica, error: str) -> None:
        self.router.mark_failed(rep.replica_id, error)
        self._refresh_replica_gauges()
        self._broadcast({"event": "replica_failed",
                         "replica": rep.replica_id, "role": rep.role,
                         "error": error[:200]})
        # incident capture rides router.mark_failed (ISSUE 15) — the
        # single chokepoint both planes and the silent-signal path hit

    def pull_timeline(self, session_id: Optional[str] = None,
                      trace_id: Optional[str] = None) -> dict:
        """One session's ordered lifecycle across every replica
        (ISSUE 15): in-process replicas share the process-wide span
        ring, so the pull is local — the wire twin lives on
        FabricPlane.pull_timeline."""
        return fleetobs.assemble_timeline(
            fleetobs.SPANS.spans(), session_id=session_id,
            trace_id=trace_id)

    def pull_tree(self, tree_id: str) -> dict:
        """One coherent agent-tree view (ISSUE 20): in-process replicas
        share the process-wide tree registry, so the pull is local —
        the wire twin lives on FabricPlane.pull_tree."""
        from quoracle_tpu.infra import treeobs
        return treeobs.tree_payload(tree_id)

    # -- elastic topology (ISSUE 14, serving/fleet.py) --------------------

    def _recompute_modes(self) -> None:
        self.disaggregated = any(r.role == "prefill"
                                 for r in self.replicas)

    def add_replica(self, role: str = "decode") -> Replica:
        """Spin up one replica in ``role`` and register it with the
        router — the fleet controller's scale-up primitive. Requires
        the factory args ``build`` saved (or a test-injected
        ``_replica_args``)."""
        if self._replica_args is None:
            raise RuntimeError(
                "this plane has no replica factory — build it via "
                "ClusterPlane.build (or set _replica_args) before "
                "scaling")
        a = dict(self._replica_args)
        prefill = role == "prefill"
        backend = TPUBackend(
            a["pool"], seed=a["seed"], embed_model=a.get("embed_model"),
            embedder=self._embedder,
            continuous=a["continuous"] and not prefill,
            continuous_chunk=a["continuous_chunk"],
            continuous_slots=a["continuous_slots"],
            draft_map=None if prefill else a["draft_map"],
            draft_k=a["draft_k"], qos=a["qos"],
            host_kv_mb=a["host_kv_mb"] or 256,
            disk_kv_dir=a["disk_kv_dir"], disk_kv_gb=a["disk_kv_gb"],
            quantize_weights=a["quantize_weights"],
            quantize_kv=a["quantize_kv"])
        if self._embedder is None:
            self._embedder = backend.embedder
        if role in ("prefill", "decode"):
            for spec in a["pool"]:
                backend.engines[spec].role = role
        with self._lock:
            rid = f"{role}-{self._rep_seq}"
            self._rep_seq += 1
        rep = Replica(replica_id=rid, role=role, backend=backend)
        self.replicas.append(rep)
        self.router.register(rep)
        self._recompute_modes()
        self._refresh_replica_gauges()
        self._broadcast({"event": "replica_added", "replica": rid,
                         "role": role})
        return rep

    def remove_replica(self, replica_id: str) -> bool:
        """Retire a replica: deregister from the router and close its
        backend. The fleet controller drains it FIRST (live-migrating
        every resident session); calling this on an undrained replica
        loses its sessions to re-prefill — correct, just cold."""
        rep = next((r for r in self.replicas
                    if r.replica_id == replica_id), None)
        if rep is None:
            return False
        self.replicas.remove(rep)
        self.router.deregister(replica_id)
        self._recompute_modes()
        self._refresh_replica_gauges()
        try:
            rep.close()
        except Exception:                 # noqa: BLE001 — best-effort
            logger.exception("retired replica %s close failed",
                             replica_id)
        self._broadcast({"event": "replica_removed",
                         "replica": replica_id, "role": rep.role})
        return True

    # -- ModelBackend -----------------------------------------------------

    def query(self, requests: Sequence[QueryRequest]) -> list[QueryResult]:
        results: list[Optional[QueryResult]] = [None] * len(requests)
        parent = TRACER.current()
        if len(requests) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(
                    max_workers=len(requests),
                    thread_name_prefix="cluster-row") as ex:
                list(ex.map(
                    lambda i: self._serve_one(i, requests[i], results,
                                              parent),
                    range(len(requests))))
        else:
            for i, r in enumerate(requests):
                self._serve_one(i, r, results, parent)
        return [r for r in results if r is not None]

    def _serve_one(self, i: int, r: QueryRequest, results: list,
                   parent=None) -> None:
        with TRACER.use(parent):
            try:
                with fleetobs.request_span("cluster.request",
                                           r.session_id,
                                           model=r.model_spec):
                    results[i] = self._route(r)
            except AdmissionError as e:
                results[i] = QueryResult(
                    model_spec=r.model_spec,
                    error=f"admission_rejected: {e} "
                          f"(retry_after_ms={e.retry_after_ms})")
            except ReplicaFailedError as e:
                results[i] = QueryResult(
                    model_spec=r.model_spec,
                    error=f"replica_failed: {e} "
                          f"(replica={e.replica_id}, phase={e.phase})")
            except Exception as e:        # noqa: BLE001 — row-level error
                results[i] = QueryResult(
                    model_spec=r.model_spec,
                    error=f"cluster query failed: {e}")

    def _has_image(self, r: QueryRequest) -> bool:
        return any(isinstance(m.get("content"), (list, tuple))
                   and any(isinstance(p, dict) and p.get("type") in
                           ("image", "image_base64", "image_url")
                           for p in m["content"])
                   for m in r.messages)

    def _route(self, r: QueryRequest) -> QueryResult:
        """One request through the cluster: whole-request delegation for
        unified replicas / affinity hits / image rows, the split
        prefill→handoff→decode flow otherwise."""
        if r.model_spec not in self.pool:
            return QueryResult(model_spec=r.model_spec,
                               error=f"unknown model {r.model_spec!r}",
                               permanent_error=True)
        if not self.disaggregated:
            rep = self.router.place("unified", session_id=r.session_id)
            return self._delegate(rep, r, path="unified")
        affinity = self.router.affinity_of(r.session_id)
        if affinity is not None and self._session_resident(affinity, r):
            # decode rows stick to the replica holding their pages: the
            # suffix prefill of a resumed conversation runs on the
            # decode replica itself — a continuation, not tier work
            return self._delegate(affinity, r, path="affinity")
        if self._has_image(r):
            # VLM rows skip KV sessions by design (runtime.py) — there
            # is no KV to hand off; the decode tier serves them whole
            rep = self.router.place("decode", session_id=r.session_id)
            return self._delegate(rep, r, path="image")
        return self._disagg(r)

    def _session_resident(self, rep: Replica, r: QueryRequest) -> bool:
        """Any engine on the replica still holds (or hibernates) the
        session — affinity entries can outlive sessions dropped by LRU
        churn, and routing to a page-less replica would silently
        re-prefill where fresh placement could do better."""
        if not r.session_id:
            return False
        eng = rep.backend.engines.get(r.model_spec)
        return (eng is not None
                and eng.session_tokens(r.session_id) is not None)

    def _delegate(self, rep: Replica, r: QueryRequest,
                  path: str) -> QueryResult:
        CLUSTER_REQUESTS_TOTAL.inc(replica=rep.replica_id, path=path)
        try:
            # Chaos seam (ISSUE 11): a "crash" here is a replica dying
            # while serving a delegated request — recovered through the
            # SAME mark-failed path a real device/transport death takes.
            CHAOS.fire("cluster.serve", replica=rep.replica_id)
            out = rep.backend.query([r])
        except Exception as e:            # noqa: BLE001 — replica-fatal
            self._mark_failed(rep, repr(e))
            raise ReplicaFailedError(
                f"replica {rep.replica_id} failed serving a "
                f"{path} request: {e}", replica_id=rep.replica_id,
                phase=path)
        if out and r.session_id and out[0].ok:
            self.router.set_affinity(r.session_id, rep.replica_id)
        return out[0] if out else QueryResult(
            model_spec=r.model_spec, error="replica returned no result")

    # -- the disaggregated flow ------------------------------------------

    def _disagg(self, r: QueryRequest) -> QueryResult:
        spec = r.model_spec
        t0 = time.monotonic()
        pre = self.router.place("prefill")
        # Row preparation on the PREFILL backend: identical tokenize/
        # splice/budget semantics to the monolithic path (runtime.py
        # _build_rows — one construction, zero drift). Fresh rows have
        # no resident session anywhere, so the splice is inert.
        tmp: list = [None]
        rows, live = pre.backend._build_rows(spec, [0], [r], tmp, t0)
        if not live:
            return tmp[0]                 # overflow / pre-dispatch deadline
        row = rows[0]
        hid = r.session_id or self._own_session_id()
        owns = r.session_id is None
        fleetobs.tag_current_span(hid)
        pe = pre.backend.engines[spec]
        CLUSTER_REQUESTS_TOTAL.inc(replica=pre.replica_id, path="disagg")
        t_pre = time.monotonic()
        try:
            g1 = pe.generate(
                [row["prompt"]], temperature=row["temperature"],
                top_p=row["top_p"], max_new_tokens=1,
                session_ids=[hid],
                constrain_json=[row["constrain_json"]],
                action_enums=[row["action_enum"]])[0]
        except Exception as e:            # noqa: BLE001 — replica-fatal
            self._mark_failed(pre, repr(e))
            # cold fallback: the whole request on a decode replica —
            # slower (no prefill tier), never wrong
            rep = self.router.place("decode")
            return self._delegate(rep, r, path="failover")
        js = g1.json_state if row["constrain_json"] else None
        try:
            env = self.handoff.export(pe, hid, spec,
                                      src_replica=pre.replica_id,
                                      json_state=js)
        except HandoffError as e:
            # no envelope → nothing to adopt; decode replica re-prefills
            # the whole prompt (cold). Correctness never depends on the
            # handoff succeeding.
            logger.warning("handoff export failed (%s); cold re-prefill",
                           e)
            rep = self.router.place("decode", session_id=r.session_id)
            return self._delegate(rep, r, path="failover")
        if TRACER.active():
            pre_ms = (time.monotonic() - t_pre) * 1000
            TRACER.emit("cluster.prefill", pre_ms,
                        ts=time.time() - pre_ms / 1000.0, session=hid,
                        model=spec, replica=pre.replica_id)
        try:
            return self._decode_phase(r, row, g1, env, hid, owns, t0)
        finally:
            self.handoff.forget(spec, hid)

    def _decode_phase(self, r: QueryRequest, row: dict, g1, env,
                      hid: str, owns: bool, t0: float,
                      exclude: tuple = ()) -> QueryResult:
        spec = r.model_spec
        dec = self.router.place("decode", exclude=exclude)
        t_dec = time.monotonic()
        try:
            self.handoff.adopt(dec.backend.engines[spec], env,
                               dst_replica=dec.replica_id)
        except HandoffError:
            # signature mismatch: version-skewed pair. The decode side
            # re-prefills cold — reject the BYTES, not the request.
            rep = self.router.place("decode", session_id=r.session_id,
                                    exclude=exclude)
            return self._delegate(rep, r, path="failover")
        budget = row["budget"]
        done = g1.finish_reason == "stop" or budget <= 1
        try:
            if done:
                g_ids, g2 = list(g1.token_ids), None
            else:
                g2 = self._decode_on(dec, spec, row, g1, hid)
                g_ids = list(g1.token_ids) + list(g2.token_ids)
        except AdmissionError:
            # the chosen replica shed: another decode replica may have
            # headroom — the front door only sheds when EVERY eligible
            # replica does (the last re-raise propagates the reject)
            remaining = [rep2 for rep2 in self.router.replicas("decode")
                         if rep2.replica_id
                         not in exclude + (dec.replica_id,)]
            if not remaining:
                raise
            return self._decode_phase(
                r, row, g1, env, hid, owns, t0,
                exclude=exclude + (dec.replica_id,))
        except Exception as e:            # noqa: BLE001 — replica death
            self._mark_failed(dec, repr(e))
            survivors = self.router.alive_count("decode")
            if survivors and self.handoff.inflight(spec, hid) is not None:
                # re-place through the retained envelope: the surviving
                # replica adopts the SAME prefill KV and decode reruns
                # from the handoff point — at temperature 0 the rerun is
                # bit-identical, so mid-stream death is invisible in the
                # output
                self.handoff.note_replaced(spec)
                from quoracle_tpu.infra.flightrec import FLIGHT
                FLIGHT.record("kv_handoff_replace", model=spec,
                              session=hid, failed=dec.replica_id)
                self._broadcast({"event": "row_replaced", "model": spec,
                                 "failed_replica": dec.replica_id})
                return self._decode_phase(
                    r, row, g1, env, hid, owns, t0,
                    exclude=exclude + (dec.replica_id,))
            from quoracle_tpu.infra.telemetry import (
                CLUSTER_HANDOFFS_TOTAL,
            )
            CLUSTER_HANDOFFS_TOTAL.inc(model=spec,
                                       status="replace_failed")
            raise ReplicaFailedError(
                f"decode replica {dec.replica_id} died mid-stream and "
                f"no surviving decode replica could adopt the row: {e}",
                replica_id=dec.replica_id, phase="decode")
        if TRACER.active():
            dec_ms = (time.monotonic() - t_dec) * 1000
            TRACER.emit("cluster.decode", dec_ms,
                        ts=time.time() - dec_ms / 1000.0, session=hid,
                        model=spec, replica=dec.replica_id)
        de = dec.backend.engines[spec]
        if owns:
            de.drop_session(hid)
        elif r.session_id:
            self.router.set_affinity(r.session_id, dec.replica_id)
        CLUSTER_REQUESTS_TOTAL.inc(replica=dec.replica_id, path="disagg")
        cfg = de.cfg
        n_prompt = g1.n_prompt_tokens
        latency_ms = (time.monotonic() - t0) * 1000
        cost = (n_prompt * cfg.input_cost_per_mtok
                + len(g_ids) * cfg.output_cost_per_mtok) / 1e6
        return QueryResult(
            model_spec=spec,
            # one decode over the concatenated ids — BPE merges across
            # the phase boundary must render exactly as a monolithic run
            text=de.tokenizer.decode(g_ids),
            usage=Usage(n_prompt, len(g_ids), cost),
            latency_ms=latency_ms,
            # split-phase serving: the per-call prefill/decode split is
            # not meaningful (same convention as continuous mode)
            prefill_ms=0.0, decode_ms=0.0,
            cached_tokens=g1.n_cached_tokens,
            spec_rounds=getattr(g2, "spec_rounds", 0),
            spec_accepted_tokens=getattr(g2, "spec_accepted_tokens", 0))

    def _decode_on(self, dec: Replica, spec: str, row: dict, g1,
                   hid: str):
        """The continuation (prompt + first token) on the decode
        replica: through its continuous batcher when it runs one (the
        production path — speculation included), a direct engine call
        otherwise."""
        # Chaos seam (ISSUE 11): decode-replica death AFTER the handoff
        # landed — the retained envelope must re-place the row onto a
        # survivor with bit-identical output (kv_handoff_replace), or
        # fail it with a structured error naming replica + phase.
        CHAOS.fire("cluster.decode", replica=dec.replica_id)
        continuation = list(row["prompt"]) + list(g1.token_ids)
        remaining = row["budget"] - len(g1.token_ids)
        js = g1.json_state if row["constrain_json"] else None
        cb = dec.backend._cbatchers.get(spec)
        if cb is not None:
            fut = cb.submit(
                continuation, temperature=row["temperature"],
                top_p=row["top_p"], max_new_tokens=remaining,
                session_id=hid, constrain_json=row["constrain_json"],
                action_enum=row["action_enum"],
                priority=row["priority"], tenant=row["tenant"],
                deadline_s=row["deadline_s"],
                initial_json_state=js,
                task_id=row.get("task_id"), decide=row.get("decide"),
                tree=row.get("tree"))
            return fut.result()
        de = dec.backend.engines[spec]
        return de.generate(
            [continuation], temperature=row["temperature"],
            top_p=row["top_p"], max_new_tokens=remaining,
            session_ids=[hid], constrain_json=[row["constrain_json"]],
            action_enums=[row["action_enum"]],
            initial_json_state=[js])[0]

    # -- pool-wide backend surface ---------------------------------------

    @property
    def engines(self) -> dict:
        """Replica-qualified engine map ("<replica>@<spec>") — keeps the
        resource attribution, dashboards, and HBM accounting
        (infra/resources.py) working over the whole cluster without a
        special case ("@" because model specs may themselves contain
        "/")."""
        out = {}
        for rep in self.replicas:
            for spec, e in rep.backend.engines.items():
                out[f"{rep.replica_id}@{spec}"] = e
        return out

    @property
    def draft_map(self) -> dict:
        """Replica-qualified draft wiring, same key scheme as
        ``engines`` — the HBM attribution's draft-role tagging."""
        out = {}
        for rep in self.replicas:
            for t, d in rep.backend.draft_map.items():
                out[f"{rep.replica_id}@{t}"] = f"{rep.replica_id}@{d}"
        return out

    def swap_draft(self, tspec: str, engine_factory, *,
                   name: Optional[str] = None) -> list:
        """Plane-level draft hot-swap (ISSUE 19): every live replica
        whose backend drafts ``tspec`` receives its OWN engine from
        ``engine_factory`` (separate session stores — a shared engine
        would alias paged KV across replicas). Returns
        ``[(replica_id, incumbent_engine)]`` for instant rollback.
        The fleet controller's ``swap_draft`` is the production path —
        per-replica quiesce plus the deterministic action ledger; this
        primitive is what it (and the mono promoter) drive."""
        out = []
        for rep in self.replicas:
            if not rep.alive or tspec not in rep.backend.draft_map:
                continue
            out.append((rep.replica_id,
                        rep.backend.swap_draft(tspec, engine_factory(),
                                               name=name)))
        return out

    @property
    def qos_controller(self):
        """The web edge's shed gate (server._qos_shed): the ROUTER is
        the cluster's admission surface — it sheds only when every
        eligible replica sheds, with the max retry-after."""
        if any(getattr(rep.backend, "qos_controller", None) is not None
               for rep in self.replicas):
            return self.router
        return None

    def attach_bus(self, bus) -> None:
        self._bus = bus
        for rep in self.replicas:
            rep.backend.attach_bus(bus)

    def watchdog_sources(self) -> list:
        out = []
        for rep in self.replicas:
            out.extend((f"{rep.replica_id}:{name}", fn)
                       for name, fn in rep.backend.watchdog_sources())
        return out

    def scheduler_stats(self) -> dict:
        return {f"{rep.replica_id}/{spec}": st
                for rep in self.replicas
                for spec, st in rep.backend.scheduler_stats().items()}

    def qos_stats(self) -> dict:
        per = {rep.replica_id: rep.backend.qos_stats()
               for rep in self.replicas}
        enabled = any(p.get("enabled") for p in per.values())
        return {"enabled": enabled, "cluster": True, "replicas": per,
                "router": self.router.stats() if enabled else None}

    def spec_stats(self) -> dict:
        per = {rep.replica_id: rep.backend.spec_stats()
               for rep in self.replicas}
        return {"enabled": any(p.get("enabled") for p in per.values()),
                "cluster": True, "replicas": per}

    def kv_stats(self) -> dict:
        per = {rep.replica_id: rep.backend.kv_stats()
               for rep in self.replicas}
        return {"enabled": any(p.get("enabled") for p in per.values()),
                "cluster": True, "replicas": per,
                "handoff": self.handoff.stats()}

    def cluster_stats(self) -> dict:
        """GET /api/cluster payload: topology + router + handoff +
        per-replica health in one read."""
        self._refresh_replica_gauges()
        return {
            "enabled": True,
            "disaggregated": self.disaggregated,
            "pool": list(self.pool),
            "replicas": [{
                "replica_id": rep.replica_id,
                "role": rep.role,
                "alive": rep.alive,
                "scheduler": rep.backend.scheduler_stats(),
            } for rep in self.replicas],
            "router": self.router.stats(),
            "handoff": self.handoff.stats(),
        }

    def prefetch_sessions(self, session_id: str) -> int:
        rep = self.router.affinity_of(session_id)
        if rep is not None:
            return rep.backend.prefetch_sessions(session_id)
        return 0

    def drop_session(self, session_id: str,
                     model_specs: Optional[Sequence[str]] = None) -> None:
        for rep in self.replicas:
            rep.backend.drop_session(session_id, model_specs)
        if model_specs is None:
            self.router.drop_affinity(session_id)

    def embed(self, texts: Sequence[str]) -> list[np.ndarray]:
        return self.replicas[0].backend.embed(texts)

    def count_tokens(self, model_spec: str, text: str) -> int:
        return self.replicas[0].backend.count_tokens(model_spec, text)

    def context_window(self, model_spec: str) -> int:
        return self.replicas[0].backend.context_window(model_spec)

    def output_limit(self, model_spec: str) -> int:
        return self.replicas[0].backend.output_limit(model_spec)
