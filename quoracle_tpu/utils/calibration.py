"""Measured gates for the paged-attention fast paths.

The ragged paged kernels trade per-launch overhead for not materializing
the [B, maxp·page] contiguous working cache. WHERE that trade wins is a
property of the deployment, not the code: through a remote-dispatch relay
a pallas launch costs ~2.7 ms and the gather path wins even at 16k
resident tokens; on a local-dispatch host the same launch is ~µs
(BASELINE.md "Long-context regime"). Hardcoding either answer bakes one
deployment's quirk into the engine (VERDICT r3 weak #2), so the gates are
DATA:

  * ``tools/calibrate_paged.py`` measures the gather/direct crossover on
    the current host and writes it here;
  * ``load_paged_gates()`` reads that file (env override
    ``QUORACLE_PAGED_CALIB``; explicit constructor args beat both);
  * absent a calibration file the direct paths stay off — the
    conservative default, now a *documented absence of data* rather than
    a magic constant.

File format (JSON): {"decode_min_resident": int|null,
"prefill_min_resident": int|null, "prefill_max_chunk": int,
"measured_on": str, "device_kind": str} — null disables that path.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

_OFF = 1 << 30


@dataclasses.dataclass(frozen=True)
class PagedGates:
    """Resident-token thresholds enabling the direct (ragged-kernel) paged
    paths; ``_OFF`` (2**30) disables. ``prefill_max_chunk`` bounds the
    dense intra-chunk O(T²) piece of the direct prefill — longer chunks
    take the standard path (they're mostly-fresh prefills, which never
    gather a prefix anyway).

    ``unified_min_resident`` gates the UNIFIED ragged kernel (ISSUE 8 —
    one mixed prefill+decode launch, KV written straight to pages). Its
    default differs from the direct gates: ``None`` means AUTO — ON
    (threshold 0) on TPU, off elsewhere — because the unified kernel is
    the intended default serving path on TPU and needs no calibration
    file to engage; gather is the measured FALLBACK a calibration run
    can reinstate per geometry (tools/calibrate_paged.py measures
    unified-vs-gather and writes an explicit threshold or ``"off"``).
    Old calibration files without the key keep their direct/decode gates
    and get AUTO for unified (backward compatible)."""

    decode_min_resident: int = _OFF
    prefill_min_resident: int = _OFF
    prefill_max_chunk: int = 1024
    unified_min_resident: Optional[int] = None   # None = AUTO (TPU: on)
    source: str = "default (no calibration file)"


def default_calib_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "quoracle_tpu", "paged_gates.json")


def load_paged_gates(path: Optional[str] = None) -> PagedGates:
    p = (path or os.environ.get("QUORACLE_PAGED_CALIB")
         or default_calib_path())
    try:
        with open(p) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError):
        return PagedGates()

    # The crossover is a property of THIS host's dispatch regime: gates
    # measured on a local-dispatch dev box must not govern a
    # remote-dispatch relay deployment that happens to share a cache dir
    # (launch cost differs ~1000×). A recorded device_kind that doesn't
    # match the current device invalidates the file.
    recorded = raw.get("device_kind") or ""
    if recorded:
        try:
            import jax
            current = getattr(jax.devices()[0], "device_kind", "")
        except Exception:
            current = ""
        if current and recorded != current:
            import logging
            logging.getLogger(__name__).warning(
                "ignoring paged-gate calibration %s: measured on %r, "
                "running on %r — recalibrate with tools/calibrate_paged",
                p, recorded, current)
            return PagedGates(
                source=f"default (calibration {p} is for {recorded!r}, "
                       f"not {current!r})")

    def gate(key: str) -> int:
        v = raw.get(key)
        return _OFF if v is None else int(v)

    # unified gate (ISSUE 8): ABSENT key (old files) = AUTO; explicit
    # JSON null = measured off (gather wins on this geometry)
    _absent = object()
    u = raw.get("unified_min_resident", _absent)
    unified = None if u is _absent else (_OFF if u is None else int(u))

    return PagedGates(
        decode_min_resident=gate("decode_min_resident"),
        prefill_min_resident=gate("prefill_min_resident"),
        prefill_max_chunk=int(raw.get("prefill_max_chunk", 1024)),
        unified_min_resident=unified,
        source=p,
    )


def resolve_unified_gate(gates: PagedGates) -> int:
    """The unified ragged kernel's effective threshold: an explicit
    calibrated value wins; AUTO (no file / old file) resolves to ON
    (threshold 0) on TPU — the flip the kernel exists for — and off on
    other backends, where the fused gather programs stay the measured
    default and tests opt in explicitly."""
    if gates.unified_min_resident is not None:
        return int(gates.unified_min_resident)
    try:
        import jax
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:     # noqa: BLE001 — no backend = no kernel
        on_tpu = False
    return 0 if on_tpu else _OFF


_UNSET = object()


def save_paged_gates(path: Optional[str], *, decode_min_resident,
                     prefill_min_resident, prefill_max_chunk: int = 1024,
                     unified_min_resident=_UNSET,
                     device_kind: str = "", note: str = "") -> str:
    """Write a calibration file (tools/calibrate_paged.py's output).
    ``unified_min_resident`` omitted = the key is left out of the file
    (AUTO on load); explicit None = measured off (JSON null)."""
    import datetime
    p = path or default_calib_path()
    os.makedirs(os.path.dirname(p), exist_ok=True)
    payload = {
        "decode_min_resident": decode_min_resident,
        "prefill_min_resident": prefill_min_resident,
        "prefill_max_chunk": prefill_max_chunk,
        "device_kind": device_kind,
        "note": note,
        "measured_on": datetime.datetime.now(
            datetime.timezone.utc).isoformat(),
    }
    if unified_min_resident is not _UNSET:
        payload["unified_min_resident"] = unified_min_resident
    with open(p, "w") as f:
        json.dump(payload, f, indent=1)
    return p
