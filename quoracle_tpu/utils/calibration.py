"""Measured gates for the paged-attention fast paths.

The ragged paged kernels trade per-launch overhead for not materializing
the [B, maxp·page] contiguous working cache. WHERE that trade wins is a
property of the deployment, not the code: through a remote-dispatch relay
a pallas launch costs ~2.7 ms and the gather path wins even at 16k
resident tokens; on a local-dispatch host the same launch is ~µs
(BASELINE.md "Long-context regime"). Hardcoding either answer bakes one
deployment's quirk into the engine (VERDICT r3 weak #2), so the gates are
DATA:

  * ``tools/calibrate_paged.py`` measures the gather/direct crossover on
    the current host and writes it here;
  * ``load_paged_gates()`` reads that file (env override
    ``QUORACLE_PAGED_CALIB``; explicit constructor args beat both);
  * absent a calibration file the direct paths stay off — the
    conservative default, now a *documented absence of data* rather than
    a magic constant.

File format (JSON): {"decode_min_resident": int|null,
"prefill_min_resident": int|null, "prefill_max_chunk": int,
"measured_on": str, "device_kind": str} — null disables that path.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

_OFF = 1 << 30


@dataclasses.dataclass(frozen=True)
class PagedGates:
    """Resident-token thresholds enabling the direct (ragged-kernel) paged
    paths; ``_OFF`` (2**30) disables. ``prefill_max_chunk`` bounds the
    dense intra-chunk O(T²) piece of the direct prefill — longer chunks
    take the standard path (they're mostly-fresh prefills, which never
    gather a prefix anyway)."""

    decode_min_resident: int = _OFF
    prefill_min_resident: int = _OFF
    prefill_max_chunk: int = 1024
    source: str = "default (no calibration file)"


def default_calib_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "quoracle_tpu", "paged_gates.json")


def load_paged_gates(path: Optional[str] = None) -> PagedGates:
    p = (path or os.environ.get("QUORACLE_PAGED_CALIB")
         or default_calib_path())
    try:
        with open(p) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError):
        return PagedGates()

    # The crossover is a property of THIS host's dispatch regime: gates
    # measured on a local-dispatch dev box must not govern a
    # remote-dispatch relay deployment that happens to share a cache dir
    # (launch cost differs ~1000×). A recorded device_kind that doesn't
    # match the current device invalidates the file.
    recorded = raw.get("device_kind") or ""
    if recorded:
        try:
            import jax
            current = getattr(jax.devices()[0], "device_kind", "")
        except Exception:
            current = ""
        if current and recorded != current:
            import logging
            logging.getLogger(__name__).warning(
                "ignoring paged-gate calibration %s: measured on %r, "
                "running on %r — recalibrate with tools/calibrate_paged",
                p, recorded, current)
            return PagedGates(
                source=f"default (calibration {p} is for {recorded!r}, "
                       f"not {current!r})")

    def gate(key: str) -> int:
        v = raw.get(key)
        return _OFF if v is None else int(v)

    return PagedGates(
        decode_min_resident=gate("decode_min_resident"),
        prefill_min_resident=gate("prefill_min_resident"),
        prefill_max_chunk=int(raw.get("prefill_max_chunk", 1024)),
        source=p,
    )


def save_paged_gates(path: Optional[str], *, decode_min_resident,
                     prefill_min_resident, prefill_max_chunk: int = 1024,
                     device_kind: str = "", note: str = "") -> str:
    """Write a calibration file (tools/calibrate_paged.py's output)."""
    import datetime
    p = path or default_calib_path()
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "w") as f:
        json.dump({
            "decode_min_resident": decode_min_resident,
            "prefill_min_resident": prefill_min_resident,
            "prefill_max_chunk": prefill_max_chunk,
            "device_kind": device_kind,
            "note": note,
            "measured_on": datetime.datetime.now(
                datetime.timezone.utc).isoformat(),
        }, f, indent=1)
    return p
