"""SHA-256-keyed LRU cache with TTL.

Parity with the reference's ETS embedding cache semantics — SHA-256 text keys,
1h TTL, 1000-entry cap (reference lib/quoracle/models/embeddings.ex:23-25,
65-95) — as a plain object handed explicitly to its users (no process/global
state; the reference needed a GenServer ETS owner, we don't).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict

from quoracle_tpu.analysis.lockdep import named_lock
from typing import Any, Callable, Optional


def text_key(text: str, namespace: str = "") -> str:
    return hashlib.sha256((namespace + "\x00" + text).encode("utf-8")).hexdigest()


class TTLCache:
    """Thread-safe LRU with per-entry TTL. clock is injectable for tests."""

    def __init__(self, max_entries: int = 1000, ttl_s: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic):
        self.max_entries = max_entries
        self.ttl_s = ttl_s
        self._clock = clock
        self._data: OrderedDict[str, tuple[float, Any]] = OrderedDict()
        self._lock = named_lock("cache.lru")
        self.hits = 0
        self.misses = 0

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            item = self._data.get(key)
            if item is None:
                self.misses += 1
                return None
            ts, value = item
            if self._clock() - ts > self.ttl_s:
                del self._data[key]
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = (self._clock(), value)
            self._data.move_to_end(key)
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)

    def __len__(self) -> int:
        return len(self._data)
