"""HTML → Markdown conversion for fetched pages.

The reference converts with the htmd library (reference
lib/quoracle/actions/web.ex:12-36 — fetch → HTML-to-Markdown → truncate).
This is a stdlib html.parser implementation covering the structures agents
actually read: headings, paragraphs, lists, links, emphasis, code,
blockquotes, tables (flattened), with script/style/nav noise dropped.
"""

from __future__ import annotations

import re
from html.parser import HTMLParser

_SKIP = {"script", "style", "noscript", "svg", "head", "iframe", "canvas"}
_BLOCK = {"p", "div", "section", "article", "li", "tr", "br", "table",
          "ul", "ol", "blockquote", "pre", "header", "footer", "nav",
          "h1", "h2", "h3", "h4", "h5", "h6"}


class _MdExtractor(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.out: list[str] = []
        self._skip_depth = 0
        self._href: str | None = None
        self._list_stack: list[str] = []
        self._in_pre = False

    # -- tag handling -------------------------------------------------

    def handle_starttag(self, tag, attrs):
        if tag in _SKIP:
            self._skip_depth += 1
            return
        if self._skip_depth:
            return
        a = dict(attrs)
        if tag in ("h1", "h2", "h3", "h4", "h5", "h6"):
            self.out.append("\n\n" + "#" * int(tag[1]) + " ")
        elif tag == "a":
            self._href = a.get("href")
            self.out.append("[")
        elif tag in ("b", "strong"):
            self.out.append("**")
        elif tag in ("i", "em"):
            self.out.append("*")
        elif tag == "code" and not self._in_pre:
            self.out.append("`")
        elif tag == "pre":
            self._in_pre = True
            self.out.append("\n\n```\n")
        elif tag in ("ul", "ol"):
            self._list_stack.append(tag)
        elif tag == "li":
            marker = ("- " if not self._list_stack
                      or self._list_stack[-1] == "ul" else "1. ")
            self.out.append("\n" + "  " * max(0, len(self._list_stack) - 1)
                            + marker)
        elif tag == "blockquote":
            self.out.append("\n\n> ")
        elif tag == "img":
            alt = a.get("alt") or "image"
            src = a.get("src", "")
            self.out.append(f"![{alt}]({src})")
        elif tag in ("td", "th"):
            self.out.append(" | ")
        elif tag in _BLOCK:
            self.out.append("\n\n")

    def handle_endtag(self, tag):
        if tag in _SKIP:
            self._skip_depth = max(0, self._skip_depth - 1)
            return
        if self._skip_depth:
            return
        if tag == "a":
            href = self._href or ""
            self._href = None
            self.out.append(f"]({href})" if href else "]")
        elif tag in ("b", "strong"):
            self.out.append("**")
        elif tag in ("i", "em"):
            self.out.append("*")
        elif tag == "code" and not self._in_pre:
            self.out.append("`")
        elif tag == "pre":
            self._in_pre = False
            self.out.append("\n```\n")
        elif tag in ("ul", "ol"):
            if self._list_stack:
                self._list_stack.pop()
            self.out.append("\n")
        elif tag in _BLOCK:
            self.out.append("\n")

    def handle_data(self, data):
        if self._skip_depth:
            return
        if self._in_pre:
            self.out.append(data)
        else:
            self.out.append(re.sub(r"\s+", " ", data))


def html_to_markdown(html: str) -> str:
    parser = _MdExtractor()
    try:
        parser.feed(html)
        parser.close()
    except Exception:
        pass  # best-effort on malformed HTML; keep what was extracted
    text = "".join(parser.out)
    text = re.sub(r"[ \t]+\n", "\n", text)
    text = re.sub(r"\n{3,}", "\n\n", text)
    return text.strip()
