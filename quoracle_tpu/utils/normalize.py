"""Result normalization + truncation utilities.

Parity with the reference's Utils.JSONNormalizer / ContentStringifier /
ResponseTruncator (reference lib/quoracle/utils/ — SURVEY.md §2.6): action
results and histories must serialize to JSON deterministically before they
enter model context or the DB, multimodal content must flatten to text for
token counting, and oversized outputs must truncate with an explicit marker
rather than silently blowing the context window.
"""

from __future__ import annotations

import json
from typing import Any

TRUNCATION_MARKER = "\n...[truncated {omitted} of {total} chars]..."
DEFAULT_MAX_CHARS = 30_000


def normalize_json(value: Any) -> Any:
    """Make a value JSON-serializable: tuples/sets -> lists, exceptions ->
    tagged dicts, bytes -> utf-8 (replace), unknown objects -> repr. The
    reference normalizes Elixir tuples to tagged JSON
    (json_normalizer.ex); our equivalent hazard is Python-only types."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, bytes):
        return value.decode("utf-8", errors="replace")
    if isinstance(value, dict):
        return {str(k): normalize_json(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [normalize_json(v) for v in value]
    if isinstance(value, (set, frozenset)):
        # key=repr: mixed-type sets ({1, "a"}) have no natural order and
        # plain sorted() raises TypeError; repr gives a deterministic one.
        return sorted((normalize_json(v) for v in value), key=repr)
    if isinstance(value, BaseException):
        return {"error": type(value).__name__, "message": str(value)}
    if hasattr(value, "__dict__") and not isinstance(value, type):
        try:
            return {"type": type(value).__name__,
                    **{k: normalize_json(v) for k, v in vars(value).items()}}
        except Exception:
            pass
    return repr(value)


def to_json(value: Any, **kwargs: Any) -> str:
    return json.dumps(normalize_json(value), ensure_ascii=False,
                      sort_keys=True, **kwargs)


def stringify_content(content: Any) -> str:
    """Flatten chat-message content (string or multimodal part list) to plain
    text for token counting / logging (reference content_stringifier.ex).
    Image parts become placeholder markers sized like their token cost is
    accounted elsewhere."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = []
        for part in content:
            if isinstance(part, str):
                parts.append(part)
            elif isinstance(part, dict):
                if part.get("type") == "text":
                    parts.append(str(part.get("text", "")))
                elif part.get("type") in ("image", "image_url",
                                          "image_base64"):
                    # NEVER inline image payloads: a base64 body would blow
                    # text-only members' windows and wreck token budgeting
                    parts.append("[image]")
                else:
                    parts.append(to_json(part))
            else:
                parts.append(str(part))
        return "\n".join(parts)
    if isinstance(content, dict):
        return to_json(content)
    return str(content)


def truncate_response(text: str, max_chars: int = DEFAULT_MAX_CHARS) -> str:
    """Head+tail truncation with an explicit marker (reference
    response_truncator.ex). Keeps both ends: shell output errors usually live
    at the tail, context at the head."""
    if len(text) <= max_chars:
        return text
    marker = TRUNCATION_MARKER.format(
        omitted=len(text) - max_chars, total=len(text))
    keep = max_chars - len(marker)
    head = keep * 2 // 3
    tail = keep - head
    return text[:head] + marker + (text[-tail:] if tail > 0 else "")
