"""Persistent XLA compilation cache.

First-touch compiles dominate cold starts here: a 1b-scale
(prefill, decode) pair costs 10-20 s through the remote-compile helper,
and a growing conversation crossing a shape bucket pays again. JAX's
persistent cache keys compiled executables by (HLO, flags, platform) on
disk, so every process after the first reuses them — measured on this
deployment: 9.0 s → 1.1 s for a fresh-process recompile. bench.py and the
Runtime's TPU backend both enable it (the mock backend never compiles, so
it skips the setup).
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT = os.path.expanduser("~/.cache/quoracle_tpu/xla")
_enabled: Optional[str] = None


def enable_compilation_cache(path: Optional[str] = None) -> str:
    """Idempotent: points JAX's persistent compilation cache at ``path``
    (default ~/.cache/quoracle_tpu/xla, overridable with
    QUORACLE_XLA_CACHE; QUORACLE_XLA_CACHE=off disables). Returns the
    directory in use ("" when disabled)."""
    global _enabled
    if _enabled is not None:
        return _enabled
    path = path or os.environ.get("QUORACLE_XLA_CACHE") or _DEFAULT
    if path.lower() in ("off", "none", "0"):
        _enabled = ""
        return _enabled
    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache everything that took real compile time, however small the HLO
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _enabled = path
    return _enabled
