"""Shared utilities (caches, JSON handling, redaction, truncation)."""
