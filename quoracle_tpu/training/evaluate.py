"""Offline acceptance evaluation (ISSUE 19): the promotion evidence.

A candidate draft is judged the way production will judge it — a
held-out slice of captured contexts is replayed through the REAL
:meth:`GenerateEngine.verify_chunk` path: the draft proposes greedily
from its own paged sessions, the target verifies the chunk exactly as
``BatchedSpeculator.run_round`` would, and the accepted-prefix length
is the score. No proxy metric (loss, perplexity) stands in for the
quantity the fleet actually monetizes.

Replay is UNCONSTRAINED greedy: the captured round's grammar state is
not part of the record (it is derived serving state), and candidate vs
incumbent are compared on identical terms against the same live target
engine, so the comparison — the only thing the gate consumes — is
exact. Greedy-equality sanity runs the full speculative loop
(:class:`BatchedSpeculator` on a local row shim) against vanilla
engine decode: a candidate that diverges at temp 0 is broken at the
algorithm level and never promotes, whatever its acceptance.
"""

from __future__ import annotations

import statistics
from typing import Optional, Sequence

from quoracle_tpu.infra.telemetry import TRAIN_EVAL_ACCEPTANCE


def _pct(xs: Sequence[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    idx = min(len(ys) - 1, int(round(q * (len(ys) - 1))))
    return ys[idx]


def replay_acceptance(target_engine, draft_engine, examples, *,
                      max_k: int = 8, batch: int = 8,
                      session_prefix: str = "flywheel-eval") -> dict:
    """Replay captured contexts: the draft proposes up to the round's
    original chunk length (capped at ``max_k``), the target verifies in
    one chunk, acceptance = accepted prefix / proposed. Sessions are
    created per example and dropped after — the engines' stores end
    exactly as they started."""
    eos = draft_engine.cfg.eos_token_id
    rates: list[float] = []
    todo = [rec for rec in examples
            if rec.get("kind") == "spec_round" and rec.get("ctx")
            and rec.get("proposal")]
    for lo in range(0, len(todo), batch):
        chunk = todo[lo:lo + batch]
        ctxs = [list(r["ctx"]) for r in chunk]
        k_req = [max(1, min(max_k, len(r["proposal"]))) for r in chunk]
        sids = [f"{session_prefix}-{lo + i}" for i in range(len(chunk))]
        n = len(chunk)
        try:
            drafts = draft_engine.generate(
                ctxs, temperature=0.0, top_p=1.0, max_new_tokens=k_req,
                session_ids=sids, constrain_json=[False] * n,
                action_enums=[None] * n, initial_json_state=[None] * n)
            proposals = []
            for g, kq in zip(drafts, k_req):
                p = list(g.token_ids)
                if g.finish_reason == "stop" and len(p) < kq:
                    p.append(eos)
                proposals.append(p or [eos])
            vres = target_engine.verify_chunk(
                [c + p[:-1] for c, p in zip(ctxs, proposals)], sids,
                [len(p) for p in proposals],
                temperature=[0.0] * n, constrain_json=[False] * n,
                action_enums=[None] * n, initial_json_state=[None] * n,
                need_probs=False)
            for props, v in zip(proposals, vres):
                ids = v["ids"]
                j = 0
                for t, d in enumerate(props):
                    if d != int(ids[t]):
                        break
                    j += 1
                rates.append(j / max(1, len(props)))
        finally:
            for sid in sids:
                draft_engine.drop_session(sid)
                target_engine.drop_session(sid)
    return {
        "n": len(rates),
        "p50": round(_pct(rates, 0.50), 4),
        "p95": round(_pct(rates, 0.95), 4),
        "mean": round(statistics.fmean(rates), 4) if rates else 0.0,
    }


def compare(target_engine, incumbent_engine, candidate_engine,
            examples, *, max_k: int = 8, batch: int = 8) -> dict:
    """Candidate vs incumbent on the SAME held-out slice against the
    SAME target engine. The per-role acceptance gauges land so a
    dashboard sees the evidence the gate saw."""
    model = target_engine.cfg.name
    report = {"model": model}
    for role, engine in (("incumbent", incumbent_engine),
                         ("candidate", candidate_engine)):
        stats = replay_acceptance(target_engine, engine, examples,
                                  max_k=max_k, batch=batch,
                                  session_prefix=f"flywheel-{role}")
        report[role] = stats
        for stat in ("p50", "p95", "mean"):
            TRAIN_EVAL_ACCEPTANCE.set(stats[stat], model=model,
                                      role=role, stat=stat)
    report["margin_p50"] = round(
        report["candidate"]["p50"] - report["incumbent"]["p50"], 4)
    return report


# ---------------------------------------------------------------------------
# Greedy-equality sanity: the full speculative loop vs vanilla decode
# ---------------------------------------------------------------------------


class _EvalRow:
    """The scheduler-row shape ``BatchedSpeculator.run_round`` drives
    (tests/test_spec_serving.py's shim, made reusable)."""

    __slots__ = ("prompt", "emitted", "temperature", "top_p", "max_new",
                 "session_id", "constrain", "action_enum", "json_state",
                 "spec_rounds", "spec_drafted", "spec_accepted",
                 "chip_ms", "n_cached_first")

    def __init__(self, prompt: list, max_new: int, session_id: str):
        self.prompt = list(prompt)
        self.emitted: list = []
        self.temperature = 0.0
        self.top_p = 1.0
        self.max_new = max_new
        self.session_id = session_id
        self.constrain = False
        self.action_enum = None
        self.json_state: Optional[int] = None
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.chip_ms = 0.0
        self.n_cached_first: Optional[int] = None


def greedy_equal(target_engine, draft_engine, prompts, *, k: int = 4,
                 max_new: int = 32,
                 session_prefix: str = "flywheel-sanity") -> bool:
    """True iff speculative temp-0 decode with this draft is
    bit-identical to vanilla engine decode on every prompt — the
    correctness gate a candidate must pass regardless of acceptance."""
    from quoracle_tpu.models.speculative import BatchedSpeculator
    spec = BatchedSpeculator(target_engine, draft_engine, k=k,
                             accept_floor=0.0)
    ok = True
    for i, prompt in enumerate(prompts):
        want = target_engine.generate([list(prompt)], temperature=0.0,
                                      max_new_tokens=max_new)[0]
        sid = f"{session_prefix}-{i}"
        row = _EvalRow(prompt, max_new, sid)
        try:
            while len(row.emitted) < max_new:
                finishes = spec.run_round([row])
                if finishes[id(row)] == "stop":
                    break
        finally:
            spec.drop_session(sid)
            target_engine.drop_session(sid)
        if row.emitted != list(want.token_ids):
            ok = False
    return ok
