"""Serving flywheel (ISSUE 19): capture -> train -> evaluate -> promote.

The fleet already generates its own training signal — accepted/rejected
speculation chunks (ISSUE 6) and consensus winners with full audit
records (ISSUE 5) — and the fleet controller's drain (ISSUE 14) gives
zero-downtime model hot-swap. This package connects them:

* :mod:`quoracle_tpu.training.capture` — a bounded, crash-safe,
  append-only replay store of training examples tapped read-only off
  the serving path (``QUORACLE_TRAIN_CAPTURE=0`` kills the plane;
  temp-0 bits are identical either way).
* :mod:`quoracle_tpu.training.trainer` — a pjit data-parallel
  distillation trainer over ``parallel/mesh`` submeshes: hard CE on
  target corrections + acceptance-weighted CE on accepted chunks.
* :mod:`quoracle_tpu.training.evaluate` — offline acceptance replay of
  a held-out capture slice through the REAL ``verify_chunk`` path.
* :mod:`quoracle_tpu.training.promote` — the bench-gated promotion:
  margin + greedy-equality gate, per-replica drain/hot-swap through
  the fleet controller's deterministic ledger, instant rollback, and
  a live acceptance-regression guard that auto-rolls back.
* :mod:`quoracle_tpu.training.draft_check` — the subsumed
  ``tools/train_draft.py`` smoke (``--check`` now exercises the pjit
  step on a 1-device mesh so the sharded path is in tier-1).
"""
