"""pjit draft-distillation trainer (ISSUE 19): capture → weights.

Grows ``tools/train_draft.py``'s single-device loop into a real
trainer:

* **Sharded step.** One jitted train step laid out over a
  ``parallel/mesh`` submesh with ``NamedSharding`` — batches split on
  the ``dp`` axis (``data_spec``'s convention), params/optimizer state
  replicated, grad psum riding ICI exactly like the multichip dry-run
  in models/train.py. A 1-device mesh is the degenerate case the
  ``--check`` smoke exercises in tier-1, so the sharded path itself is
  gated, not just the math.
* **Distillation loss.** Weighted next-token CE against the RECORDED
  target tokens: the correction position (where the target overruled
  the draft) gets weight 1.0 — hard CE on exactly the tokens the draft
  got wrong in production — and accepted positions get
  ``accept_weight`` so the draft keeps rehearsing what already works
  without drowning the corrections.
* **Deterministic data order.** Batch slots index into the row set via
  sha256(seed:step:slot) — the chaos-plane idiom — so a training run
  is replayable from (rows, config) alone: no RNG state to checkpoint.
* **Checkpointing with resume.** Orbax TrainState saves (the
  models/train.py substrate) to ``<ckpt_dir>/latest`` every
  ``ckpt_every`` steps plus a meta sidecar; a restart resumes at the
  saved step with the same data order (sha256 is stateless).
* **Bounded.** ``steps`` and ``budget_s`` both stop the loop — the
  trainer is built to soak off-peak elastic capacity or idle
  prefill-tier chips, where the budget is the contract.

No new kernels: the step reuses the serving transformer's ``forward``
(models/train.py's choice), so the accelerator guides' kernel rules are
inherited, not re-implemented.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from quoracle_tpu.infra.telemetry import TRAIN_LOSS, TRAIN_STEPS_TOTAL
from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.train import (
    TrainState, load_train_state, save_train_state,
)
from quoracle_tpu.models.transformer import forward, init_cache
from quoracle_tpu.parallel.mesh import make_mesh


@dataclasses.dataclass
class TrainerConfig:
    steps: int = 200
    batch: int = 8
    seq: int = 256
    lr: float = 1e-3
    warmup: int = 0                 # 0 = constant lr (the legacy loop)
    clip_norm: float = 0.0          # 0 = no clipping
    weight_decay: float = 0.01
    accept_weight: float = 0.25     # CE weight on accepted positions
    seed: int = 0
    dp: int = 1                     # data-parallel submesh width
    budget_s: Optional[float] = None
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 0             # 0 = final save only (when ckpt_dir)
    log_every: int = 25


def make_optimizer(tcfg: TrainerConfig, steps: Optional[int] = None):
    """optax chain: optional global-norm clip + adamw on a warmup-cosine
    schedule (constant when warmup == 0, matching the legacy loop)."""
    steps = steps or tcfg.steps
    if tcfg.warmup > 0:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, tcfg.lr, tcfg.warmup,
            max(steps, tcfg.warmup + 1), end_value=tcfg.lr * 0.1)
    else:
        schedule = tcfg.lr
    tx = optax.adamw(schedule, weight_decay=tcfg.weight_decay)
    if tcfg.clip_norm > 0:
        return optax.chain(optax.clip_by_global_norm(tcfg.clip_norm), tx)
    return tx


# ---------------------------------------------------------------------------
# Loss: weighted CE against recorded targets
# ---------------------------------------------------------------------------


def distill_loss_fn(params: dict, cfg: ModelConfig, tokens: jax.Array,
                    targets: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted next-token CE where the label at position i+1 comes
    from ``targets`` (the recorded target-model tokens), not from the
    sequence itself — the draft ran ``tokens`` (ctx + its own
    proposals) but must learn to say what the TARGET said there.
    With targets == tokens and 0/1 weights this reduces exactly to
    models/train.py's fine-tuning loss, which is how the corpus compat
    path (draft_check) rides the same step."""
    B, T = tokens.shape
    cache = init_cache(cfg, B, T,
                       dtype=jax.tree.leaves(params)[0].dtype)
    positions = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    logits, _ = forward(params, cfg, tokens, positions, cache,
                        write_offset=jnp.zeros((B,), jnp.int32),
                        kv_lens=jnp.full((B,), T, jnp.int32))
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = targets[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    w = weights[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def distill_step(state: TrainState, cfg: ModelConfig, optimizer,
                 tokens: jax.Array, targets: jax.Array,
                 weights: jax.Array) -> tuple[TrainState, jax.Array]:
    loss, grads = jax.value_and_grad(distill_loss_fn)(
        state.params, cfg, tokens, targets, weights)
    updates, opt_state = optimizer.update(grads, state.opt_state,
                                          state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


# ---------------------------------------------------------------------------
# Rows: capture records → (tokens, targets, weights)
# ---------------------------------------------------------------------------


def rows_from_capture(records, *, seq: int, pad_id: int,
                      accept_weight: float = 0.25) -> list:
    """Project spec_round capture records onto fixed-length training
    rows. The training sequence is ctx + proposal (what the draft
    actually ran); labels at the proposal positions are the recorded
    ``verified`` target tokens. Weights: 1.0 at the correction,
    ``accept_weight`` on accepted positions, 0 elsewhere. Rows are
    LEFT-truncated (the loss positions live at the tail)."""
    rows = []
    for rec in records:
        if rec.get("kind") != "spec_round":
            continue
        ctx = rec.get("ctx") or []
        props = rec.get("proposal") or []
        ver = rec.get("verified") or []
        j = int(rec.get("accepted") or 0)
        if not props or len(ver) != len(props):
            continue
        full = list(ctx) + list(props)
        tgt = list(ctx) + list(ver)
        wts = [0.0] * len(ctx) + [
            (accept_weight if t < j else 1.0 if t == j else 0.0)
            for t in range(len(props))]
        if len(full) > seq:
            full, tgt, wts = full[-seq:], tgt[-seq:], wts[-seq:]
        if sum(wts) <= 0:
            continue
        tokens = np.full(seq, pad_id, np.int32)
        targets = np.full(seq, pad_id, np.int32)
        weights = np.zeros(seq, np.float32)
        tokens[:len(full)] = full
        targets[:len(tgt)] = tgt
        weights[:len(wts)] = wts
        rows.append((tokens, targets, weights))
    return rows


def corpus_rows(rows, *, seq: int, pad_id: int) -> list:
    """finetune.build_format_corpus's (ids, prompt_len) tuples → the
    same (tokens, targets, weights) shape: plain next-token CE on the
    completion (targets == tokens, mask past the prompt)."""
    out = []
    for ids, plen in rows:
        ids = list(ids)[:seq]
        tokens = np.full(seq, pad_id, np.int32)
        tokens[:len(ids)] = ids
        weights = np.zeros(seq, np.float32)
        weights[plen:len(ids)] = 1.0
        out.append((tokens, tokens.copy(), weights))
    return out


def heldout_split(records: Sequence, frac: float = 0.2,
                  seed: int = 0) -> tuple[list, list]:
    """Deterministic (train, heldout) split — sha256 of the record
    index, so the same capture set always splits the same way."""
    train, held = [], []
    cut = int(frac * 1_000_000)
    for i, rec in enumerate(records):
        digest = hashlib.sha256(f"{seed}:heldout:{i}".encode()).digest()
        bucket = int.from_bytes(digest[:8], "big") % 1_000_000
        (held if bucket < cut else train).append(rec)
    return train, held


# ---------------------------------------------------------------------------
# The trainer
# ---------------------------------------------------------------------------


class DraftTrainer:
    """Owns the mesh, the jitted sharded step, and the checkpoint
    cadence. ``rows`` are (tokens, targets, weights) triples from
    :func:`rows_from_capture` / :func:`corpus_rows`."""

    def __init__(self, cfg: ModelConfig, params: dict,
                 tcfg: TrainerConfig):
        assert tcfg.batch % tcfg.dp == 0, \
            f"batch {tcfg.batch} not divisible by dp={tcfg.dp}"
        self.cfg = cfg
        self.tcfg = tcfg
        self.mesh = make_mesh(n_devices=tcfg.dp, tp=1)
        self._data = NamedSharding(self.mesh, P("dp", None))
        self._repl = NamedSharding(self.mesh, P())
        self.optimizer = make_optimizer(tcfg)
        params = jax.device_put(params, self._repl)
        self.state = TrainState(params, self.optimizer.init(params),
                                jnp.asarray(0, jnp.int32))
        self._step_fn = jax.jit(
            lambda s, t, g, w: distill_step(s, cfg, self.optimizer,
                                            t, g, w),
            in_shardings=(self._repl, self._data, self._data,
                          self._data))

    # -- checkpointing ---------------------------------------------------

    def _ckpt_path(self) -> Optional[str]:
        if not self.tcfg.ckpt_dir:
            return None
        return os.path.join(self.tcfg.ckpt_dir, "latest")

    def _meta_path(self) -> str:
        return os.path.join(self.tcfg.ckpt_dir, "meta.json")

    def save(self) -> Optional[int]:
        path = self._ckpt_path()
        if path is None:
            return None
        step = int(self.state.step)
        save_train_state(path, self.state)
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "seed": self.tcfg.seed,
                       "model": self.cfg.name}, f)
        os.replace(tmp, self._meta_path())      # atomic publish
        return step

    def resume(self) -> Optional[int]:
        """Restore <ckpt_dir>/latest when present; the resumed step
        keeps the sha256 data order aligned (it is stateless in the
        step number). Returns the resumed step or None."""
        path = self._ckpt_path()
        if path is None or not os.path.exists(self._meta_path()):
            return None
        try:
            with open(self._meta_path()) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        self.state = load_train_state(path, self.state)
        return int(meta.get("step", int(self.state.step)))

    # -- the loop --------------------------------------------------------

    def _batch(self, rows: list, step: int):
        """Deterministic batch assembly: slot b of step s reads row
        sha256(seed:s:b) % len(rows) — replayable, resumable."""
        B, T = self.tcfg.batch, self.tcfg.seq
        tok = np.empty((B, T), np.int32)
        tgt = np.empty((B, T), np.int32)
        wts = np.empty((B, T), np.float32)
        for b in range(B):
            digest = hashlib.sha256(
                f"{self.tcfg.seed}:{step}:{b}".encode()).digest()
            t, g, w = rows[int.from_bytes(digest[:8], "big") % len(rows)]
            tok[b], tgt[b], wts[b] = t, g, w
        return tok, tgt, wts

    def run(self, rows: list, *,
            log: Optional[Callable[[str], Any]] = None) -> dict:
        assert rows, "no training rows"
        tcfg = self.tcfg
        resumed = self.resume()
        start = int(self.state.step)
        deadline = (time.monotonic() + tcfg.budget_s
                    if tcfg.budget_s else None)
        stopped = "steps"
        loss = None
        steps_run = 0
        t0 = time.monotonic()
        for step in range(start, tcfg.steps):
            if deadline is not None and time.monotonic() >= deadline:
                stopped = "budget"
                break
            tok, tgt, wts = self._batch(rows, step)
            self.state, loss = self._step_fn(self.state, tok, tgt, wts)
            steps_run += 1
            TRAIN_STEPS_TOTAL.inc(model=self.cfg.name)
            if step % max(1, tcfg.log_every) == 0 \
                    or step == tcfg.steps - 1:
                TRAIN_LOSS.set(float(loss), model=self.cfg.name)
                if log is not None:
                    log(f"step {step}: loss {float(loss):.4f} "
                        f"({time.monotonic() - t0:.0f}s)")
            if tcfg.ckpt_every and (step + 1) % tcfg.ckpt_every == 0:
                self.save()
        if tcfg.ckpt_dir:
            self.save()
        final = float(loss) if loss is not None else None
        if final is not None:
            TRAIN_LOSS.set(final, model=self.cfg.name)
        return {
            "model": self.cfg.name,
            "resumed_from": resumed,
            "steps_run": steps_run,
            "step": int(self.state.step),
            "final_loss": final,
            "stopped": stopped,
            "dp": tcfg.dp,
            "wall_s": round(time.monotonic() - t0, 3),
        }

    @property
    def params(self) -> dict:
        return self.state.params


# ---------------------------------------------------------------------------
# Compat entry points
# ---------------------------------------------------------------------------


def train_corpus(ckpt_dir: str, rows, steps: int, batch: int, seq: int,
                 lr: float, seed: int, log, *, dp: int = 1,
                 tcfg: Optional[TrainerConfig] = None):
    """The finetune.train contract (load HF checkpoint → train →
    (cfg, state)) on the sharded step — draft_check's ``--check`` runs
    this on a 1-device mesh so the pjit path is tier-1-gated."""
    from quoracle_tpu.models.loader import load_params, \
        register_hf_checkpoint
    cfg = register_hf_checkpoint(ckpt_dir, name="ft-base")
    params = load_params(ckpt_dir, cfg, dtype=np.float32)
    tcfg = tcfg or TrainerConfig(steps=steps, batch=batch, seq=seq,
                                 lr=lr, seed=seed, dp=dp)
    trainer = DraftTrainer(cfg, params, tcfg)
    trainer.run(corpus_rows(rows, seq=seq, pad_id=cfg.eos_token_id),
                log=log)
    return cfg, trainer.state


def train_from_capture(cfg: ModelConfig, params: dict, store,
                       tcfg: TrainerConfig, *,
                       log: Optional[Callable] = None) -> tuple:
    """One flywheel training leg: drain the capture store's spec_round
    records into rows and train. Returns (trainer, report)."""
    store.flush()
    records = list(store.read_all("spec"))
    rows = rows_from_capture(records, seq=tcfg.seq,
                             pad_id=cfg.eos_token_id,
                             accept_weight=tcfg.accept_weight)
    trainer = DraftTrainer(cfg, params, tcfg)
    report = trainer.run(rows, log=log)
    report["capture_records"] = len(records)
    report["rows"] = len(rows)
    return trainer, report
