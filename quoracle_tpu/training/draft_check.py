"""Train a REAL draft model and measure true speculative acceptance.

Subsumed from ``tools/train_draft.py`` (ISSUE 19): the training leg now
runs through :mod:`quoracle_tpu.training.trainer`'s sharded pjit step —
``--check`` exercises it on a 1-device mesh, so the data-parallel path
is gated by tier-1, not just by live bench rounds. The measurement legs
(held-out acceptance, greedy equality, the K sweep) are unchanged, and
``tools/train_draft.py`` remains importable/runnable as a thin shim.

Bench config 7 measures the self-draft CEILING (how much faster one
K-token verify chunk is than K decode steps); this tool supplies the
other factor of the realized speedup — the ACCEPTANCE RATE of an actual
small draft — by training a tiny-scale model on the same format corpus
the target was fine-tuned on (tools/finetune.py --target format) and
running speculative decoding target×draft on held-out tasks.

Tokenizer identity: the draft MUST share the target's token ids.
make_checkpoint's BPE training is deterministic in (corpus, vocab_size),
and "small" (the finetune target) and "tiny" (the draft) both use vocab
2048 over the same default corpus — the tool asserts byte-identical
tokenizer.json rather than trusting that.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python -m quoracle_tpu.tools.train_draft --steps 400 \
        --out-artifact SPECULATIVE_r05.json

Prereq: checkpoints/finetune-format/{base,tuned} from a prior
`tools/finetune.py --target format` run (the tool errors with the
command if missing).
"""

from __future__ import annotations

import argparse
import filecmp
import json
import os
import shutil
import statistics
import sys
import time


def run_check(args) -> dict:
    """``--check`` smoke mode (ISSUE 6 satellite): a self-contained,
    minutes-scale assertion that the draft-training pipeline still
    produces a USABLE draft — tiny target and tiny draft are both
    trained briefly on the same format corpus (no finetune prereq, no
    export) through the SHARDED pjit step on a 1-device mesh (ISSUE
    19), then speculative acceptance is measured on HELD-OUT format
    prompts and asserted above ``--check-floor``, with greedy
    bit-equality against vanilla engine decode as the correctness gate.
    Runs in tier-1 (tests/test_train_draft_check.py), so a regression in
    the corpus builder, the trainer, or the speculative decoder surfaces
    before a live bench round burns chip time on it."""
    import random
    import tempfile

    import jax

    from quoracle_tpu.models.generate import GenerateEngine
    from quoracle_tpu.models.make_checkpoint import make_checkpoint
    from quoracle_tpu.models.speculative import SpeculativeDecoder
    from quoracle_tpu.models.tokenizer import HFAutoTokenizer
    from quoracle_tpu.tools.finetune import (
        SYSTEM, _format_sample, build_format_corpus,
    )
    from quoracle_tpu.training.trainer import train_corpus

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    work = args.workdir or tempfile.mkdtemp(prefix="draft-check-")
    # tiny scale for BOTH: the check gates the PIPELINE (corpus →
    # trainer → acceptance), not model quality; deterministic BPE means
    # the two checkpoints share token ids (asserted below)
    t_dir = make_checkpoint(os.path.join(work, "target"), family="llama",
                            scale="tiny", seed=args.seed)
    d_dir = make_checkpoint(os.path.join(work, "draft"), family="llama",
                            scale="tiny", seed=args.seed + 7)
    a = os.path.join(t_dir, "tokenizer.json")
    b = os.path.join(d_dir, "tokenizer.json")
    if not filecmp.cmp(a, b, shallow=False):
        shutil.copy(a, b)
    tok = HFAutoTokenizer(t_dir)

    rows = build_format_corpus(tok, tok.eos_id, args.corpus_size,
                               args.seed, args.seq)
    log(f"check corpus: {len(rows)} rows; {args.steps} steps each "
        f"(pjit step, 1-device mesh)")
    tcfg, tstate = train_corpus(t_dir, rows, args.steps, args.batch,
                                args.seq, args.lr, args.seed, log, dp=1)
    dcfg, dstate = train_corpus(d_dir, rows, args.steps, args.batch,
                                args.seq, args.lr, args.seed + 1, log,
                                dp=1)

    eng = GenerateEngine(tcfg, tstate.params, tok, max_seq=512,
                         prompt_buckets=(64, 128, 256))
    dec = SpeculativeDecoder(tcfg, tstate.params, dcfg, dstate.params,
                             tok, k=args.k, max_seq=512)
    rng = random.Random(args.seed + 1)       # disjoint: held-out tasks
    acc, equal = [], 0
    for i in range(args.n_eval):
        task, _ = _format_sample(rng)
        prompt = tok.encode_chat([
            {"role": "system", "content": SYSTEM},
            {"role": "user", "content": task}])
        want = eng.generate([prompt], temperature=0.0,
                            max_new_tokens=args.max_new)[0]
        got = dec.generate(prompt, temperature=0.0,
                           max_new_tokens=args.max_new)
        acc.append(got.acceptance_rate)
        equal += int(got.token_ids == want.token_ids)
        log(f"check task {i}: accept {got.accepted}/{got.drafted} "
            f"equal={got.token_ids == want.token_ids}")
    acceptance = statistics.median(acc)
    payload = {
        "metric": "speculative_draft_check",
        "value": round(acceptance, 4),
        "unit": "acceptance_rate",
        "floor": args.check_floor,
        "k": args.k,
        "steps": args.steps,
        "greedy_equal": f"{equal}/{args.n_eval}",
        "ok": bool(acceptance >= args.check_floor
                   and equal == args.n_eval),
    }
    print(json.dumps(payload))
    assert equal == args.n_eval, \
        f"greedy speculation diverged from vanilla: {equal}/{args.n_eval}"
    assert acceptance >= args.check_floor, (
        f"draft acceptance {acceptance:.3f} below floor "
        f"{args.check_floor} — the draft-training pipeline regressed")
    return payload


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--corpus-size", type=int, default=2000)
    ap.add_argument("--k", type=int, default=6)
    ap.add_argument("--k-sweep", default=None,
                    help="comma-separated extra K values to sweep (each "
                         "measured on the same held-out tasks, "
                         "unconstrained greedy)")
    ap.add_argument("--n-eval", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=96)
    ap.add_argument("--dp", type=int, default=1,
                    help="data-parallel submesh width for the pjit "
                         "train step (batch must divide by it)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out-artifact", default=None)
    ap.add_argument("--skip-train", action="store_true",
                    help="reuse an existing draft-tuned checkpoint and "
                         "only run the acceptance measurement")
    ap.add_argument("--check", action="store_true",
                    help="smoke mode: train a tiny target + tiny draft "
                         "for a few steps on the format corpus and "
                         "assert held-out acceptance above --check-floor "
                         "(self-contained; no finetune prereq; tier-1)")
    ap.add_argument("--check-floor", type=float, default=0.2)
    args = ap.parse_args()

    if args.check:
        # check-mode defaults: small enough for a tier-1 CPU run unless
        # the caller overrode them explicitly
        if args.steps == 400:
            args.steps = 30
        if args.corpus_size == 2000:
            args.corpus_size = 300
        if args.seq == 256:
            args.seq = 192    # system prompt + task + JSON must fit
        if args.n_eval == 12:
            args.n_eval = 4
        if args.max_new == 96:
            args.max_new = 48
        if args.k == 6:
            args.k = 4
        from quoracle_tpu.utils.compile_cache import (
            enable_compilation_cache,
        )
        enable_compilation_cache()
        run_check(args)
        return

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    from quoracle_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    import numpy as np

    from quoracle_tpu.models.loader import (
        export_hf_checkpoint, load_params, register_hf_checkpoint,
        to_device,
    )
    from quoracle_tpu.models.make_checkpoint import make_checkpoint
    from quoracle_tpu.models.speculative import SpeculativeDecoder
    from quoracle_tpu.models.tokenizer import HFAutoTokenizer
    from quoracle_tpu.tools.finetune import (
        SYSTEM, _format_sample, build_format_corpus,
    )
    from quoracle_tpu.training.trainer import train_corpus

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    work = args.workdir or os.path.join(repo, "checkpoints",
                                        "finetune-format")
    target_base = os.path.join(work, "base")
    target_tuned = os.path.join(work, "tuned")
    for d in (target_base, target_tuned):
        if not os.path.isdir(d):
            raise SystemExit(
                f"missing {d}; run `python -m quoracle_tpu.tools.finetune "
                f"--target format` first")

    # --- draft base: tiny scale, byte-identical tokenizer ---------------
    draft_base = make_checkpoint(os.path.join(work, "draft-base"),
                                 family="llama", scale="tiny",
                                 seed=args.seed + 7)
    for f in ("tokenizer.json",):
        a = os.path.join(target_base, f)
        b = os.path.join(draft_base, f)
        if not filecmp.cmp(a, b, shallow=False):
            # deterministic BPE means this should never happen; if the
            # corpora ever diverge, copying restores id identity
            log(f"tokenizer {f} differs; copying target's into draft")
            shutil.copy(a, b)
    tok = HFAutoTokenizer(target_tuned)

    # --- train the draft on the SAME corpus -----------------------------
    draft_tuned = os.path.join(work, "draft-tuned")
    meta_path = os.path.join(work, "draft-meta.json")
    if args.skip_train and os.path.isdir(draft_tuned):
        log(f"reusing existing draft at {draft_tuned}")
        try:                  # the artifact records the ACTUAL provenance
            with open(meta_path) as f:
                trained_steps = json.load(f).get("steps")
        except (OSError, ValueError):      # missing OR corrupt meta
            trained_steps = None
    else:
        rows = build_format_corpus(tok, tok.eos_id, args.corpus_size,
                                   args.seed, args.seq)
        log(f"corpus: {len(rows)} rows; training tiny draft "
            f"{args.steps} steps (pjit, dp={args.dp})")
        dcfg, dstate = train_corpus(draft_base, rows, args.steps,
                                    args.batch, args.seq, args.lr,
                                    args.seed, log, dp=args.dp)
        draft_tuned = export_hf_checkpoint(
            dstate.params, dcfg, draft_tuned, draft_base)
        log(f"exported draft to {draft_tuned}")
        trained_steps = args.steps
        with open(meta_path, "w") as f:
            json.dump({"steps": trained_steps,
                       "corpus_size": args.corpus_size,
                       "seed": args.seed}, f)

    # --- speculative target x draft on held-out tasks -------------------
    tcfg = register_hf_checkpoint(target_tuned, name="spec-ft-target")
    tparams = to_device(load_params(target_tuned, tcfg, dtype=np.float32))
    dcfg2 = register_hf_checkpoint(draft_tuned, name="spec-ft-draft")
    dparams = to_device(load_params(draft_tuned, dcfg2, dtype=np.float32))

    from quoracle_tpu.models.generate import GenerateEngine
    eng = GenerateEngine(tcfg, tparams, tok, max_seq=1024,
                         prompt_buckets=(64, 128, 256))
    dec = SpeculativeDecoder(tcfg, tparams, dcfg2, dparams, tok,
                             k=args.k, max_seq=1024)

    import random
    rng = random.Random(args.seed + 1)           # disjoint: held-out tasks
    acc, tpr, van_ms, spec_ms, equal = [], [], [], [], 0
    con_acc, con_tpr, con_equal = [], [], 0
    enum = ("todo", "send_message", "wait", "execute_shell", "spawn_child")
    for i in range(args.n_eval):
        task, _ = _format_sample(rng)
        prompt = tok.encode_chat([
            {"role": "system", "content": SYSTEM},
            {"role": "user", "content": task}])
        t0 = time.monotonic()
        want = eng.generate([prompt], temperature=0.0,
                            max_new_tokens=args.max_new)[0]
        van = time.monotonic() - t0
        t0 = time.monotonic()
        got = dec.generate(prompt, temperature=0.0,
                           max_new_tokens=args.max_new)
        spc = time.monotonic() - t0
        if i > 0:                    # first call pays the spec compiles
            van_ms.append(van * 1000 / max(1, want.n_gen_tokens))
            spec_ms.append(spc * 1000 / max(1, got.n_gen_tokens))
        acc.append(got.acceptance_rate)
        tpr.append(got.tokens_per_round)
        equal += int(got.token_ids == want.token_ids)
        log(f"task {i}: accept {got.accepted}/{got.drafted} "
            f"tokens/round {got.tokens_per_round:.2f} "
            f"equal={got.token_ids == want.token_ids}")
        # grammar-constrained variant — the production consensus shape
        cwant = eng.generate([prompt], temperature=0.0,
                             max_new_tokens=args.max_new,
                             constrain_json=[True],
                             action_enums=[enum])[0]
        cgot = dec.generate(prompt, temperature=0.0,
                            max_new_tokens=args.max_new,
                            constrain_json=True, action_enum=enum)
        con_acc.append(cgot.acceptance_rate)
        con_tpr.append(cgot.tokens_per_round)
        con_equal += int(cgot.token_ids == cwant.token_ids)
        log(f"task {i} constrained: accept {cgot.accepted}/{cgot.drafted}"
            f" tokens/round {cgot.tokens_per_round:.2f} "
            f"equal={cgot.token_ids == cwant.token_ids}")

    k_sweep = {}
    if args.k_sweep:
        for kk in [int(x) for x in args.k_sweep.split(",") if x.strip()]:
            if kk == args.k:
                continue
            dk = SpeculativeDecoder(tcfg, tparams, dcfg2, dparams, tok,
                                    k=kk, max_seq=1024)
            rng_k = random.Random(args.seed + 1)
            a_list, t_list = [], []
            for _ in range(args.n_eval):
                task, _ = _format_sample(rng_k)
                prompt = tok.encode_chat([
                    {"role": "system", "content": SYSTEM},
                    {"role": "user", "content": task}])
                g = dk.generate(prompt, temperature=0.0,
                                max_new_tokens=args.max_new)
                a_list.append(g.acceptance_rate)
                t_list.append(g.tokens_per_round)
            k_sweep[str(kk)] = {
                "acceptance_p50": round(statistics.median(a_list), 4),
                "tokens_per_round_p50": round(statistics.median(t_list),
                                              2)}
            log(f"k={kk}: acceptance {k_sweep[str(kk)]}")

    payload = {
        "metric": "speculative_trained_draft",
        "value": round(statistics.median(acc), 4),
        "unit": "acceptance_rate",
        "k": args.k,
        "tokens_per_round_p50": round(statistics.median(tpr), 2),
        "greedy_equal": f"{equal}/{args.n_eval}",
        "constrained_acceptance_p50": round(
            statistics.median(con_acc), 4),
        "constrained_tokens_per_round_p50": round(
            statistics.median(con_tpr), 2),
        "constrained_greedy_equal": f"{con_equal}/{args.n_eval}",
        "constrained_enum": list(enum),
        "k_sweep": k_sweep or None,
        "target": "finetune-format/tuned (small, ~7M)",
        "draft": "finetune-format/draft-tuned (tiny, ~0.6M)",
        "draft_steps": trained_steps,
        "n_eval_heldout": args.n_eval,
        "cpu_vanilla_ms_per_token_p50": round(
            statistics.median(van_ms), 2) if van_ms else None,
        "cpu_spec_ms_per_token_p50": round(
            statistics.median(spec_ms), 2) if spec_ms else None,
        "note": ("held-out format tasks, greedy; realized chip speedup = "
                 "bench config7 ceiling x this acceptance; CPU ms are "
                 "smoke (compute-bound host, see BASELINE.md config 7)"),
    }
    line = json.dumps(payload)
    print(line)
    if args.out_artifact:
        with open(args.out_artifact, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
