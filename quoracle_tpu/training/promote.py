"""Bench-gated live promotion (ISSUE 19): the flywheel's last mile.

A candidate draft reaches production only through :class:`Promoter`,
and only when the offline evidence (:func:`quoracle_tpu.training.
evaluate.compare`) clears :func:`gate`: acceptance-p50 margin over the
incumbent AND temp-0 greedy equality. Promotion then rolls through the
fleet one replica at a time via ``FleetController.swap_draft`` — PR
14's drain/hot-swap, so in-flight work lands before each swap and
sessions stay aboard (draft KV is derived state). Every incumbent
engine is recorded before its replica swaps; any mid-rollout failure
(including an injected ``train.promote`` crash) rolls the
already-swapped replicas back to their proven incumbents before the
exception propagates.

After a successful rollout an :class:`AcceptanceGuard` arms: the live
acceptance EWMA must stay above ``offline_candidate_p50 *
floor_frac`` (the PR 5 drift idiom — consecutive-breach trip, not a
single-sample panic). A trip auto-rolls the fleet back and records a
``train_rollback`` flight event; the incumbent engines are still held,
so rollback is an in-memory pointer swap with no build/disk step.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.infra.bus import TOPIC_TRAIN
from quoracle_tpu.infra.flightrec import FLIGHT
from quoracle_tpu.infra.telemetry import TRAIN_PROMOTIONS_TOTAL


@dataclass(frozen=True)
class PromotionPolicy:
    """What a candidate must prove before (and after) it serves."""

    margin_p50: float = 0.02        # candidate p50 must beat incumbent by this
    min_examples: int = 8           # offline slice too thin -> reject
    floor_frac: float = 0.8         # live floor = candidate_p50 * floor_frac
    min_rounds: int = 20            # guard ignores EWMA before this many rounds
    trip_after: int = 3             # consecutive breaches before rollback
    require_greedy_equal: bool = True


def gate(report: dict, policy: PromotionPolicy, greedy_ok: bool) -> tuple[bool, str]:
    """The promotion decision, pure and auditable: (ok, reason)."""
    if policy.require_greedy_equal and not greedy_ok:
        return False, "greedy_mismatch"
    if report.get("n", report.get("candidate", {}).get("n", 0)) < policy.min_examples:
        return False, "too_few_examples"
    margin = report.get("margin_p50", 0.0)
    if margin < policy.margin_p50:
        return False, f"margin {margin:+.4f} < {policy.margin_p50:+.4f}"
    return True, f"margin {margin:+.4f}"


class AcceptanceGuard:
    """Live regression detector (PR 5 drift idiom, specialized): the
    offline-measured candidate p50 sets the floor; ``observe`` trips
    after ``trip_after`` consecutive EWMA samples below it."""

    def __init__(self, floor: float, policy: PromotionPolicy):
        self.floor = floor
        self.policy = policy
        self._breaches = 0
        self.tripped = False

    def observe(self, ewma: Optional[float], rounds: int) -> bool:
        """Feed one live sample; returns True exactly once, on trip."""
        if self.tripped or ewma is None or rounds < self.policy.min_rounds:
            return False
        if ewma < self.floor:
            self._breaches += 1
            if self._breaches >= self.policy.trip_after:
                self.tripped = True
                return True
        else:
            self._breaches = 0
        return False

    def stats(self) -> dict:
        return {"floor": round(self.floor, 4), "breaches": self._breaches,
                "tripped": self.tripped}


@dataclass
class _Rollout:
    """One completed promotion: everything rollback needs."""

    tspec: str
    draft_name: str
    incumbent_name: str
    incumbents: list  # [(replica_id, engine, name)] — mono replica_id None
    guard: AcceptanceGuard
    report: dict
    promoted_ts: float
    rolled_back: bool = False
    rollback_reason: Optional[str] = None


class Promoter:
    """Drives promotions and watches their aftermath. One instance per
    control plane; all mutation under the ``train.promote`` lock (rank
    2 — outermost, so the fleet/engine locks it drives nest cleanly)."""

    def __init__(self, policy: Optional[PromotionPolicy] = None):
        self.policy = policy or PromotionPolicy()
        self._lock = named_lock("train.promote")
        self._rollouts: list[_Rollout] = []
        self._rejected = 0

    # -- rollout ----------------------------------------------------------

    def promote_fleet(self, controller, tspec: str,
                      engine_factory: Callable[[], Any], *,
                      draft_name: str, report: dict,
                      greedy_ok: bool) -> dict:
        """Gate, then roll the candidate through every live replica
        serving ``tspec`` via drain/hot-swap. Atomic at fleet scope: a
        failure mid-rollout restores every already-swapped replica's
        incumbent before re-raising."""
        with self._lock:
            ok, reason = gate(report, self.policy, greedy_ok)
            model = report.get("model", tspec)
            if not ok:
                self._rejected += 1
                TRAIN_PROMOTIONS_TOTAL.inc(model=model, outcome="rejected")
                FLIGHT.record("train_promote", model=model, tspec=tspec,
                              draft=draft_name, outcome="rejected",
                              reason=reason)
                return {"promoted": False, "reason": reason}
            swapped: list = []
            incumbent_name = None
            try:
                for rep in list(controller.plane.replicas):
                    if tspec not in rep.backend.draft_map:
                        continue
                    # the serving name, not engine.cfg.name: rollback
                    # must restore the exact draft_map entry it replaced
                    prior = rep.backend.draft_map[tspec]
                    res = controller.swap_draft(
                        rep.replica_id, tspec, engine_factory,
                        draft_name=draft_name, reason="promotion")
                    if incumbent_name is None:
                        incumbent_name = prior
                    swapped.append((rep.replica_id, res["incumbent"],
                                    prior))
            except Exception:
                for replica_id, engine, prior in swapped:
                    controller.swap_draft(
                        replica_id, tspec, lambda e=engine: e,
                        draft_name=prior,
                        reason="rollback:promote_failed",
                        chaos_point=None)
                TRAIN_PROMOTIONS_TOTAL.inc(model=model, outcome="failed")
                FLIGHT.record("train_rollback", model=model, tspec=tspec,
                              draft=draft_name, outcome="failed",
                              replicas=len(swapped))
                raise
            rollout = self._arm(tspec, draft_name, incumbent_name,
                                swapped, model, report, reason)
        # broadcast AFTER the lock drops: bus handlers run inline on the
        # broadcasting thread and must not nest under train.promote
        self._announce(controller, rollout, len(swapped))
        return {"promoted": True, "reason": reason,
                "replicas": len(swapped),
                "floor": rollout.guard.floor}

    def promote_backend(self, backend, tspec: str,
                        engine_factory: Callable[[], Any], *,
                        draft_name: str, report: dict,
                        greedy_ok: bool) -> dict:
        """Mono-process variant: same gate and guard, the swap is a
        single ``TPUBackend.swap_draft`` with no drain choreography."""
        with self._lock:
            ok, reason = gate(report, self.policy, greedy_ok)
            model = report.get("model", tspec)
            if not ok:
                self._rejected += 1
                TRAIN_PROMOTIONS_TOTAL.inc(model=model, outcome="rejected")
                FLIGHT.record("train_promote", model=model, tspec=tspec,
                              draft=draft_name, outcome="rejected",
                              reason=reason)
                return {"promoted": False, "reason": reason}
            prior = backend.draft_map[tspec]
            old = backend.swap_draft(tspec, engine_factory(), name=draft_name)
            rollout = self._arm(tspec, draft_name, prior,
                                [(None, old, prior)], model, report, reason)
            return {"promoted": True, "reason": reason, "replicas": 1,
                    "floor": rollout.guard.floor}

    def _arm(self, tspec, draft_name, incumbent_name, incumbents, model,
             report, reason) -> _Rollout:
        floor = report["candidate"]["p50"] * self.policy.floor_frac
        rollout = _Rollout(tspec=tspec, draft_name=draft_name,
                           incumbent_name=incumbent_name or "?",
                           incumbents=incumbents,
                           guard=AcceptanceGuard(floor, self.policy),
                           report=report, promoted_ts=time.time())
        self._rollouts.append(rollout)
        TRAIN_PROMOTIONS_TOTAL.inc(model=model, outcome="promoted")
        FLIGHT.record("train_promote", model=model, tspec=tspec,
                      draft=draft_name, incumbent=rollout.incumbent_name,
                      outcome="promoted", reason=reason,
                      floor=round(floor, 4))
        return rollout

    def _announce(self, controller, rollout: _Rollout, n: int) -> None:
        bus = getattr(controller.plane, "_bus", None)
        if bus is not None:
            bus.broadcast(TOPIC_TRAIN, {
                "ts": time.time(), "event": "promote",
                "tspec": rollout.tspec, "draft": rollout.draft_name,
                "incumbent": rollout.incumbent_name, "replicas": n,
                "floor": round(rollout.guard.floor, 4)})

    # -- live regression watch --------------------------------------------

    def check_live(self, controller=None, backend=None) -> list[dict]:
        """Poll live acceptance for every armed rollout; auto-roll back
        any whose guard trips. Call from the control loop (or a test's
        hand crank). Returns the rollback records issued this call."""
        events: list[dict] = []
        with self._lock:
            for rollout in self._rollouts:
                if rollout.rolled_back:
                    continue
                ewma, rounds = self._live_sample(rollout.tspec,
                                                 controller, backend)
                if rollout.guard.observe(ewma, rounds):
                    events.append(self._rollback(rollout, controller,
                                                 backend, ewma))
        for ev in events:                  # broadcast outside the lock
            self._announce_rollback(controller, ev)
        return events

    def observe(self, tspec: str, ewma: Optional[float], rounds: int,
                controller=None, backend=None) -> Optional[dict]:
        """Explicit-sample variant of :meth:`check_live` for callers
        that already hold the speculator stats."""
        ev = None
        with self._lock:
            for rollout in self._rollouts:
                if rollout.rolled_back or rollout.tspec != tspec:
                    continue
                if rollout.guard.observe(ewma, rounds):
                    ev = self._rollback(rollout, controller, backend,
                                        ewma)
                    break
        if ev is not None:                 # broadcast outside the lock
            self._announce_rollback(controller, ev)
        return ev

    def _live_sample(self, tspec, controller, backend):
        ewmas, rounds = [], 0
        stats_srcs = []
        if controller is not None:
            stats_srcs = [rep.backend for rep in controller.plane.replicas
                          if tspec in rep.backend.draft_map]
        elif backend is not None:
            stats_srcs = [backend]
        for be in stats_srcs:
            member = be.spec_stats().get("members", {}).get(tspec, {})
            e = member.get("acceptance_ewma")
            if e is not None:
                ewmas.append(e)
            rounds += member.get("rounds", 0)
        ewma = min(ewmas) if ewmas else None  # worst replica trips first
        return ewma, rounds

    def _rollback(self, rollout: _Rollout, controller, backend,
                  ewma) -> dict:
        model = rollout.report.get("model", rollout.tspec)
        restored = 0
        for replica_id, engine, prior in rollout.incumbents:
            if controller is not None and replica_id is not None:
                controller.swap_draft(
                    replica_id, rollout.tspec, lambda e=engine: e,
                    draft_name=prior,
                    reason="rollback:acceptance_regression",
                    chaos_point=None)
            elif backend is not None:
                backend.swap_draft(rollout.tspec, engine, name=prior)
            restored += 1
        rollout.rolled_back = True
        rollout.rollback_reason = "acceptance_regression"
        TRAIN_PROMOTIONS_TOTAL.inc(model=model, outcome="rolled_back")
        FLIGHT.record("train_rollback", model=model, tspec=rollout.tspec,
                      draft=rollout.draft_name, outcome="regression",
                      ewma=ewma, floor=round(rollout.guard.floor, 4),
                      replicas=restored)
        return {"tspec": rollout.tspec, "draft": rollout.draft_name,
                "restored": rollout.incumbent_name, "replicas": restored,
                "ewma": ewma}

    def _announce_rollback(self, controller, ev: dict) -> None:
        if controller is None:
            return
        bus = getattr(controller.plane, "_bus", None)
        if bus is not None:
            bus.broadcast(TOPIC_TRAIN, {
                "ts": time.time(), "event": "rollback",
                "tspec": ev["tspec"], "draft": ev["draft"],
                "restored": ev["restored"], "ewma": ev["ewma"]})

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "policy": {
                    "margin_p50": self.policy.margin_p50,
                    "floor_frac": self.policy.floor_frac,
                    "trip_after": self.policy.trip_after,
                },
                "rejected": self._rejected,
                "rollouts": [{
                    "tspec": r.tspec, "draft": r.draft_name,
                    "incumbent": r.incumbent_name,
                    "margin_p50": r.report.get("margin_p50"),
                    "guard": r.guard.stats(),
                    "rolled_back": r.rolled_back,
                    "rollback_reason": r.rollback_reason,
                } for r in self._rollouts],
            }
