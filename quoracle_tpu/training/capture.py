"""Replay capture store (ISSUE 19): the flywheel's intake.

Training examples are captured at two existing seams:

* **speculation** — every :class:`BatchedSpeculator` round already
  computes, per row, the draft chunk, the per-position accept/reject
  verdict, and the target model's grammar-masked argmax at every
  position (the correction stream). That tuple IS a distillation
  example: "given this context, the target says these tokens".
* **consensus** — every decide's audit record (ISSUE 5) carries the
  winning action and its provenance; the capture plane subscribes as a
  quality sink and keeps a slim projection.

Design rules, in order:

1. **Strictly read-only on the serving path.** The taps copy row state
   after the round's commits; nothing downstream of a capture call can
   change an output bit. ``QUORACLE_TRAIN_CAPTURE=0`` kills the whole
   plane (the costobs / introspect enablement idiom) and tier-1
   asserts temp-0 on/off bit-equality across greedy, constrained and
   speculative paths.
2. **Never block, never raise.** Every failure — disk full, injected
   fault, serialization surprise — is absorbed: the record drops, a
   counter ticks, and a trip-once ``train_capture_degraded`` flight
   event lands. Chaos point ``train.capture`` fires per batch.
3. **Crash-safe by construction.** Records are crc-framed and appended
   to an in-memory buffer that seals into an immutable segment file
   via the DiskPrefixStore idiom — write tmp, ``os.replace`` publish,
   failure unlinks the tmp. A crash loses at most the unsealed buffer
   (bounded by ``segment_kb``); it can never corrupt a sealed segment.
   A sealed segment that fails its crc at read (disk rot, injected
   corruption) is skipped AND unlinked — a bad file must never poison
   a training run.
4. **Bounded.** ``budget_mb`` caps on-disk bytes; the oldest sealed
   segment is evicted first (``train_capture_evict``). Sampling is the
   sha256-of-counter idiom — deterministic, no RNG on the serving path.
5. **O(1) stats.** Byte/record totals are maintained incrementally;
   the only directory walk is the one recovery scan at open (PR 16's
   lesson: nothing on the scrape path lists files).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from typing import Any, Iterator, Optional

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.infra.flightrec import FLIGHT
from quoracle_tpu.infra.telemetry import (
    TRAIN_CAPTURE_BYTES, TRAIN_CAPTURE_EVICTIONS_TOTAL,
    TRAIN_CAPTURE_RECORDS_TOTAL,
)

# ---------------------------------------------------------------------------
# Enablement (the costobs / introspect idiom)
# ---------------------------------------------------------------------------


def _env_enabled() -> bool:
    return os.environ.get("QUORACLE_TRAIN_CAPTURE", "1").strip().lower() \
        not in ("0", "false", "off")


class _State:
    __slots__ = ("enabled",)

    def __init__(self) -> None:
        self.enabled = _env_enabled()


_STATE = _State()


def enabled() -> bool:
    return _STATE.enabled


def enable() -> None:
    _STATE.enabled = True


def disable() -> None:
    _STATE.enabled = False


# ---------------------------------------------------------------------------
# On-disk format
# ---------------------------------------------------------------------------

# Segment: MAGIC, then frames back to back. Frame: little-endian
# (payload_len, crc32(payload)) header + utf-8 canonical-JSON payload.
MAGIC = b"QCAP1\n"
_FRAME = struct.Struct("<II")
# how many trailing context tokens a speculation example keeps — enough
# to re-prefill a verify replay, bounded so one chatty session cannot
# eat the budget
CTX_TAIL = 512


def _frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) \
        + payload


def _encode(record: dict) -> bytes:
    return _frame(json.dumps(record, sort_keys=True,
                             separators=(",", ":")).encode("utf-8"))


class CaptureStore:
    """Bounded, crash-safe, append-only store of training examples.

    Thread-safe: appends land from the scheduler thread (speculation
    tap) and the consensus engine's thread (quality sink); reads come
    from the trainer. All shared state lives under the coarse
    ``train.capture`` lock — the sealed-segment write under it is the
    lock's declared purpose.
    """

    def __init__(self, path: str, *, budget_mb: float = 256.0,
                 segment_kb: int = 256, sample_every: int = 1,
                 seed: int = 0):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self.budget_bytes = max(1, int(budget_mb * (1 << 20)))
        self.segment_bytes = max(1024, int(segment_kb) << 10)
        self.sample_every = max(1, int(sample_every))
        self.seed = int(seed)
        self._lock = named_lock("train.capture")
        self._buf: list[bytes] = []
        self._buf_bytes = 0
        self._buf_records = 0
        # sealed-segment ledger: (fname, bytes, records) oldest first.
        # Totals are maintained incrementally — stats() is O(1).
        self._segments: list[tuple[str, int, int]] = []
        self._disk_bytes = 0
        self._disk_records = 0
        self._seq = 0
        self._sample_counts: dict[str, int] = {}
        self._appended = 0
        self._sampled_out = 0
        self._dropped = 0
        self._evicted_segments = 0
        self._corrupt_segments = 0
        self._recover()

    # -- recovery (the one directory walk, at open) ----------------------

    def _recover(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.path)
                           if n.startswith("cap-") and n.endswith(".qcr"))
        except OSError:
            names = []
        for name in names:
            full = os.path.join(self.path, name)
            counted = self._scan_segment(full)
            if counted is None:
                # corrupt (torn tail record, rot): skip AND unlink — the
                # DiskPrefixStore boundary; surviving segments stand
                self._unlink(full)
                self._corrupt_segments += 1
                continue
            nbytes, nrec = counted
            self._segments.append((name, nbytes, nrec))
            self._disk_bytes += nbytes
            self._disk_records += nrec
        if self._segments:
            self._seq = int(self._segments[-1][0][4:-4]) + 1
        TRAIN_CAPTURE_BYTES.set(float(self._disk_bytes))

    @staticmethod
    def _scan_segment(full: str) -> Optional[tuple[int, int]]:
        """(bytes, records) when every frame validates, else None."""
        try:
            with open(full, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if not data.startswith(MAGIC):
            return None
        off, nrec = len(MAGIC), 0
        while off < len(data):
            if off + _FRAME.size > len(data):
                return None
            ln, crc = _FRAME.unpack_from(data, off)
            off += _FRAME.size
            payload = data[off:off + ln]
            if len(payload) != ln \
                    or zlib.crc32(payload) & 0xFFFFFFFF != crc:
                return None
            off += ln
            nrec += 1
        return len(data), nrec

    def _unlink(self, full: str) -> None:
        try:
            os.unlink(full)
        except OSError:
            pass

    # -- append path -----------------------------------------------------

    def _sampled_in(self, source: str) -> bool:
        """Deterministic sha256-of-counter sampling (the chaos-plane
        idiom) — replayable, no RNG on the serving path."""
        with self._lock:
            n = self._sample_counts.get(source, 0)
            self._sample_counts[source] = n + 1
        if self.sample_every <= 1:
            return True
        digest = hashlib.sha256(
            f"{self.seed}:{source}:{n}".encode()).digest()
        return int.from_bytes(digest[:8], "big") % self.sample_every == 0

    def append(self, source: str, record: dict, *,
               corrupt: bool = False) -> str:
        """Append one record; returns its disposition. ``corrupt`` is
        the chaos plane's hook: the frame is written with a flipped
        payload byte so the read boundary must reject it."""
        if not self._sampled_in(source):
            self._sampled_out += 1
            return "sampled_out"
        framed = _encode(dict(record, source=source))
        if corrupt and len(framed) > _FRAME.size:
            body = bytearray(framed)
            body[-1] ^= 0xFF
            framed = bytes(body)
        sealed = evicted = None
        with self._lock:
            self._buf.append(framed)
            self._buf_bytes += len(framed)
            self._buf_records += 1
            self._appended += 1
            if self._buf_bytes >= self.segment_bytes:
                sealed = self._seal_locked()
                evicted = self._evict_locked()
        self._emit(sealed, evicted)
        return "ok"

    def flush(self) -> None:
        """Seal the in-memory buffer (trainer handoff / shutdown)."""
        with self._lock:
            sealed = self._seal_locked()
            evicted = self._evict_locked()
        self._emit(sealed, evicted)

    def _seal_locked(self) -> Optional[tuple[str, int, int]]:
        if not self._buf:
            return None
        name = f"cap-{self._seq:08d}.qcr"
        full = os.path.join(self.path, name)
        tmp = full + ".tmp"
        body = MAGIC + b"".join(self._buf)
        try:
            with open(tmp, "wb") as f:
                f.write(body)
            os.replace(tmp, full)          # atomic publish
        except OSError:
            self._unlink(tmp)
            raise
        self._seq += 1
        entry = (name, len(body), self._buf_records)
        self._segments.append(entry)
        self._disk_bytes += len(body)
        self._disk_records += self._buf_records
        self._buf = []
        self._buf_bytes = 0
        self._buf_records = 0
        return entry

    def _evict_locked(self) -> Optional[tuple[int, int]]:
        """Oldest-first eviction to the byte budget; (bytes, records)
        given up, or None."""
        freed_b = freed_r = 0
        while self._disk_bytes > self.budget_bytes \
                and len(self._segments) > 1:
            name, nbytes, nrec = self._segments.pop(0)
            self._unlink(os.path.join(self.path, name))
            self._disk_bytes -= nbytes
            self._disk_records -= nrec
            freed_b += nbytes
            freed_r += nrec
            self._evicted_segments += 1
        return (freed_b, freed_r) if freed_b else None

    def _emit(self, sealed, evicted) -> None:
        """Metrics/flight outside the lock (repo discipline)."""
        if sealed is not None or evicted is not None:
            TRAIN_CAPTURE_BYTES.set(float(self._disk_bytes))
        if evicted is not None:
            TRAIN_CAPTURE_EVICTIONS_TOTAL.inc()
            FLIGHT.record("train_capture_evict",
                          bytes=evicted[0], records=evicted[1])

    # -- read path (trainer side — not scraped) --------------------------

    def read_all(self, source: Optional[str] = None) -> Iterator[dict]:
        """Yield every stored record oldest-first, sealed segments then
        the unsealed buffer. A frame that fails its crc mid-segment
        skips the REST of that segment and unlinks it — surviving
        records before the corruption are still yielded."""
        with self._lock:
            names = [n for n, _, _ in self._segments]
            buffered = list(self._buf)
        for name in names:
            full = os.path.join(self.path, name)
            try:
                with open(full, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            ok, records = self._decode_segment(data)
            if not ok:
                self._drop_segment(name)
            for rec in records:
                if source is None or rec.get("source") == source:
                    yield rec
        for framed in buffered:
            payload = framed[_FRAME.size:]
            ln, crc = _FRAME.unpack_from(framed, 0)
            if len(payload) != ln \
                    or zlib.crc32(payload) & 0xFFFFFFFF != crc:
                continue
            rec = json.loads(payload.decode("utf-8"))
            if source is None or rec.get("source") == source:
                yield rec

    @staticmethod
    def _decode_segment(data: bytes) -> tuple[bool, list[dict]]:
        records: list[dict] = []
        if not data.startswith(MAGIC):
            return False, records
        off = len(MAGIC)
        while off < len(data):
            if off + _FRAME.size > len(data):
                return False, records
            ln, crc = _FRAME.unpack_from(data, off)
            off += _FRAME.size
            payload = data[off:off + ln]
            if len(payload) != ln \
                    or zlib.crc32(payload) & 0xFFFFFFFF != crc:
                return False, records
            off += ln
            try:
                records.append(json.loads(payload.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                return False, records
        return True, records

    def _drop_segment(self, name: str) -> None:
        """Corrupt segment seen at read: unlink + ledger adjust."""
        with self._lock:
            for i, (n, nbytes, nrec) in enumerate(self._segments):
                if n == name:
                    self._segments.pop(i)
                    self._disk_bytes -= nbytes
                    self._disk_records -= nrec
                    self._corrupt_segments += 1
                    break
            else:
                return
        self._unlink(os.path.join(self.path, name))
        TRAIN_CAPTURE_BYTES.set(float(self._disk_bytes))
        FLIGHT.record("kv_disk_corrupt", path=name, plane="train.capture")

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        """O(1) — every total is maintained incrementally."""
        with self._lock:
            return {
                "path": self.path,
                "budget_mb": round(self.budget_bytes / (1 << 20), 2),
                "sample_every": self.sample_every,
                "disk_bytes": self._disk_bytes,
                "disk_records": self._disk_records,
                "segments": len(self._segments),
                "buffered_records": self._buf_records,
                "buffered_bytes": self._buf_bytes,
                "appended": self._appended,
                "sampled_out": self._sampled_out,
                "dropped": self._dropped,
                "evicted_segments": self._evicted_segments,
                "corrupt_segments": self._corrupt_segments,
                "full": self._disk_bytes >= self.budget_bytes,
            }


# ---------------------------------------------------------------------------
# The plane: a process-wide singleton the serving taps talk to
# ---------------------------------------------------------------------------


class _Plane:
    """Holds the installed store (if any) and absorbs every failure.
    ``active`` is the serving taps' one-attribute-read fast path."""

    def __init__(self) -> None:
        self.store: Optional[CaptureStore] = None
        self._degraded = False          # trip-once flight guard
        self._install_lock = threading.Lock()

    @property
    def active(self) -> bool:
        return _STATE.enabled and self.store is not None

    def install(self, path: str, **kwargs: Any) -> CaptureStore:
        with self._install_lock:
            store = CaptureStore(path, **kwargs)
            self.store = store
            self._degraded = False
            return store

    def uninstall(self) -> None:
        with self._install_lock:
            store = self.store
            self.store = None
        if store is not None:
            try:
                store.flush()
            except Exception:             # noqa: BLE001 — shutdown only
                pass

    def reset(self) -> None:
        """Test hook: drop the store and restore env enablement."""
        with self._install_lock:
            self.store = None
            self._degraded = False
        _STATE.enabled = _env_enabled()

    # -- the two taps ----------------------------------------------------

    def observe_spec_round(self, model: str, draft: str,
                           examples: list) -> None:
        """Speculation tap: one call per round, AFTER the commits, with
        copies — see models/speculative.py. Never raises."""
        self._append_batch("spec", model, examples)

    def observe_consensus(self, record: dict) -> None:
        """Quality-sink tap (consensus/quality.py): keep the winning
        proposal + prompt context as a slim projection."""
        if not self.active:
            return
        if record.get("event") != "consensus_audit":
            return
        decision = record.get("decision") or None
        if not decision:
            return
        slim = {
            "kind": "consensus",
            "decide_id": record.get("decide_id"),
            "task_id": record.get("task_id"),
            "agent_id": record.get("agent_id"),
            "action": decision.get("action"),
            "action_kind": decision.get("kind"),
            "confidence": decision.get("confidence"),
            "n_members": record.get("n_members"),
            "margin": record.get("margin"),
            "winners": [m for m, st in (record.get("members")
                                        or {}).items()
                        if st.get("agreed")],
        }
        self._append_batch("consensus", "-", [slim])

    def _append_batch(self, source: str, model: str,
                      records: list) -> None:
        store = self.store
        if not _STATE.enabled or store is None or not records:
            return
        ok = dropped = sampled_out = 0
        try:
            # chaos seam: one decision per batch. drop → the batch is
            # lost; corrupt → frames land with a flipped byte so the
            # read boundary must reject them; crash → absorbed below
            # exactly like a real disk failure.
            from quoracle_tpu.chaos.faults import CHAOS
            fault = CHAOS.fire("train.capture", model=model)
            corrupt = False
            if fault is not None:
                if fault.kind == "drop":
                    dropped = len(records)
                    records = []
                elif fault.kind == "corrupt":
                    corrupt = True
            for rec in records:
                disp = store.append(source, rec, corrupt=corrupt)
                if disp == "ok":
                    ok += 1
                else:
                    sampled_out += 1
        except Exception:                 # noqa: BLE001 — rule 2: the
            # serving path absorbs everything (disk full, injected
            # crash, serialization surprise); the record drops
            dropped += max(0, len(records) - ok - sampled_out)
            with store._lock:
                store._dropped += dropped
            if not self._degraded:
                self._degraded = True
                FLIGHT.record("train_capture_degraded",
                              source=source, model=model)
        else:
            if dropped:
                with store._lock:
                    store._dropped += dropped
        if ok:
            TRAIN_CAPTURE_RECORDS_TOTAL.inc(ok, source=source,
                                            status="ok")
        if sampled_out:
            TRAIN_CAPTURE_RECORDS_TOTAL.inc(sampled_out, source=source,
                                            status="sampled_out")
        if dropped:
            TRAIN_CAPTURE_RECORDS_TOTAL.inc(dropped, source=source,
                                            status="dropped")

    def stats(self) -> dict:
        store = self.store
        payload: dict = {
            "enabled": _STATE.enabled,
            "installed": store is not None,
            "degraded": self._degraded,
        }
        if store is not None:
            payload["store"] = store.stats()
        return payload


CAPTURE = _Plane()


def spec_example(ctx: list, proposal: list, verified: list,
                 accepted: int, correction: Optional[int],
                 temperature: float, constrain: bool,
                 action_enum) -> dict:
    """One speculation training example — the schema ARCHITECTURE §22
    documents. ``verified`` is the target's grammar-masked argmax at
    every proposal position (the distillation targets); ``accepted`` is
    the prefix length the round committed; ``correction`` is the
    target's token at the first reject (None on full accept)."""
    dropped = max(0, len(ctx) - CTX_TAIL)
    return {
        "kind": "spec_round",
        "ctx": [int(t) for t in ctx[-CTX_TAIL:]],
        "ctx_dropped": dropped,
        "proposal": [int(t) for t in proposal],
        "verified": [int(t) for t in verified],
        "accepted": int(accepted),
        "correction": None if correction is None else int(correction),
        "temperature": float(temperature),
        "constrain": bool(constrain),
        "action_enum": (sorted(action_enum)
                        if action_enum else None),
    }
