"""Multi-host distributed backend: DCN-spanning meshes over XLA collectives.

The reference's "distributed communication backend" is OTP messaging +
Phoenix.PubSub on ONE BEAM node (SURVEY.md §2.9 — no NCCL/MPI anywhere);
scaling past one host there means nothing. Here multi-host IS first-class:
``init_process`` joins this process into a JAX distributed system (TPU
pods: ICI within a slice, DCN between hosts; CPU tests: Gloo over
localhost), and ``multihost_mesh`` lays the global device set out so the
heavy collectives stay on the fast network:

  * tp (tensor parallel)  — INNERMOST, always within one host's devices:
    per-layer psums ride ICI, never DCN;
  * dp (data parallel)    — OUTERMOST, across hosts: one grad all-reduce
    per step is the only DCN traffic (the scaling-book recipe);
  * sp (sequence parallel)— between the two: ring hops prefer neighbors.

Everything downstream is unchanged — param_specs/cache_spec/shard_map name
axes, never device counts, so the same serving and train steps jit over a
multihost mesh exactly as over a single-host one. tests/test_distributed.py
proves it by running a REAL two-process mesh (Gloo collectives across
process boundaries) on CPU: global train steps produce identical replicated
losses on every host.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass
class ProcessInfo:
    process_id: int
    num_processes: int
    local_devices: int
    global_devices: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def _cluster_env_expects_peers() -> bool:
    """True when the environment says MULTIPLE processes should form a
    cluster — then an auto-init failure must surface, not degrade to a
    silent 1/N-of-the-pod run. Mere key PRESENCE is not enough: single-host
    TPU VMs routinely export TPU_WORKER_HOSTNAMES with one (or a garbage)
    entry, and crashing those would break every single-host serve."""
    import os
    if (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
            or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")):
        return True
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True                              # >= 2 workers listed
    for key in ("OMPI_COMM_WORLD_SIZE", "SLURM_NTASKS"):
        try:
            if int(os.environ.get(key, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def init_process(coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None) -> ProcessInfo:
    """Join the JAX distributed system. On TPU pods all three arguments are
    usually inferred from the environment (jax.distributed.initialize()
    with no args); CPU/GPU clusters pass them explicitly. With no arguments
    AND no cluster environment, degrades to single-process operation — but
    when the environment says a cluster exists, an init failure re-raises:
    swallowing it would leave this process training on 1/N of the pod or
    hanging in the first collective its peers enter without it."""
    import logging

    import jax

    def _info() -> ProcessInfo:
        return ProcessInfo(
            process_id=jax.process_index(),
            num_processes=jax.process_count(),
            local_devices=jax.local_device_count(),
            global_devices=jax.device_count(),
        )

    try:
        from jax._src import distributed as _dist
        already = _dist.global_state.client is not None
    except Exception:
        already = False
    if already:
        # a second Runtime / repeated call in one process: the system is
        # up, just report it
        return _info()
    if process_id is not None and coordinator_address is None \
            and num_processes is None:
        # an explicit rank with nothing to join would silently degrade to
        # a single-process run with the rank dropped — the exact failure
        # mode this module exists to surface
        raise ValueError(
            "process_id given without coordinator_address/num_processes — "
            "pass all three for explicit clusters, or none for pod "
            "auto-detection")
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    else:
        try:
            jax.distributed.initialize()
        except Exception as e:
            if _cluster_env_expects_peers():
                raise
            logging.getLogger(__name__).debug(
                "no cluster environment; single-process operation (%s)", e)
    return _info()


def _hosts_of(devs: Sequence) -> list[list]:
    """Group devices by owning process, in process order, and require the
    groups to be even — the reshape below assumes a rectangular
    [hosts, local] layout."""
    by_proc: dict[int, list] = {}
    for d in devs:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    groups = [by_proc[p] for p in sorted(by_proc)]
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        # ValueError (not assert): this guard must survive `python -O` —
        # a ragged host layout silently reshaped would misplace shards.
        raise ValueError(
            f"uneven devices per host: "
            f"{ {p: len(g) for p, g in by_proc.items()} }")
    return groups


def multihost_mesh(tp: Optional[int] = None, sp: int = 1,
                   devices: Optional[Sequence] = None):
    """Global dp×(sp×)tp mesh over every process's devices with tp packed
    inside a host. Host membership comes from each device's own
    ``process_index`` (never from list length), so explicit device lists —
    including cross-host ones — get the same tp-within-host guarantee:
    per-layer tp psums ride ICI, and only the dp axis crosses DCN. The
    mesh itself is built by make_mesh over the host-ordered device list
    (one reshape implementation for single- and multi-host)."""
    from quoracle_tpu.parallel.mesh import make_mesh
    import jax
    devs = list(devices) if devices is not None else jax.devices()
    hosts = _hosts_of(devs)
    n_local = len(hosts[0])
    tp = tp or 1
    if n_local % tp != 0:
        # ValueError (not assert): stripped asserts under `python -O` would
        # let a cross-host tp mesh build silently — the exact cross-DCN-psum
        # hang this module exists to prevent.
        raise ValueError(
            f"tp={tp} must divide the per-host device count {n_local} (tp "
            f"stays within one host so its collectives ride ICI, not DCN)")
    ordered = [d for g in hosts for d in g]
    return make_mesh(devices=ordered, tp=tp, sp=sp)


def host_local_batch(global_batch, mesh, spec):
    """Each host feeds its own shard of a dp-sharded batch: wraps
    multihost_utils.host_local_array_to_global_array so callers hand the
    PER-HOST numpy slice and get the global jax.Array laid out on the
    mesh. On a single process this is just device_put with the sharding."""
    import jax
    from jax.sharding import NamedSharding
    if jax.process_count() == 1:
        return jax.device_put(global_batch, NamedSharding(mesh, spec))
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        global_batch, mesh, spec)


def barrier(tag: str = "barrier") -> None:
    """Cross-host sync point (no-op single-process)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)
