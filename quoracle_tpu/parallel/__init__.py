"""Device-mesh parallelism: sharding specs + collectives layout.

TPU-only layer with no reference counterpart — the reference's "distribution"
is actor concurrency on one BEAM node (SURVEY.md §2.9); model-level
parallelism here is new capability: tensor parallel within a pool member,
data parallel across consensus batch rows, sequence parallel (ring attention)
for long context, all expressed as jax.sharding annotations over one Mesh so
XLA inserts ICI collectives.
"""

from quoracle_tpu.parallel.distributed import (  # noqa: F401
    ProcessInfo,
    barrier,
    host_local_batch,
    init_process,
    multihost_mesh,
)
from quoracle_tpu.parallel.mesh import (  # noqa: F401
    cache_spec,
    data_spec,
    make_mesh,
    param_specs,
    shard_params,
)
