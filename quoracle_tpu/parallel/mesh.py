"""Mesh construction + PartitionSpecs for the model runtime.

Sharding philosophy (scaling-book recipe): pick a mesh, annotate params and
activations with NamedSharding, let XLA/GSPMD insert the collectives, which
ride ICI. Axes:

  dp — data parallel: consensus batch rows ([model-pool member x agent] rows)
  tp — tensor parallel: attention heads / ffn columns within one pool member
  sp — sequence parallel: long-context ring attention (ops/ring_attention.py)

A 3-model pool on a v5e-8 is three sub-meshes (static chip partition, host
scheduler launches the three generates concurrently) OR one mesh where the
pool rides the dp axis; both are expressible here because specs only name
axes, never device counts.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quoracle_tpu.models.config import ModelConfig


def make_mesh(
    n_devices: Optional[int] = None,
    tp: Optional[int] = None,
    axis_names: Optional[Sequence[str]] = None,
    devices: Optional[Sequence] = None,
    sp: int = 1,
) -> Mesh:
    """Build a dp×tp mesh — or dp×sp×tp when sp > 1 (sequence-parallel
    ring attention over the middle axis: ppermute hops ride neighboring
    ICI links).

    tp defaults to all remaining devices (dp=1): latency-optimal for a
    single agent's consensus round; callers raise dp when many agents
    decode concurrently.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    assert n % sp == 0, f"{n} devices not divisible by sp={sp}"
    tp = tp or n // sp
    assert n % (sp * tp) == 0, \
        f"{n} devices not divisible by sp*tp={sp * tp}"
    if axis_names is None:
        axis_names = ("dp", "sp", "tp") if sp > 1 else ("dp", "tp")
    if sp > 1:
        arr = np.array(devs).reshape(n // (sp * tp), sp, tp)
    else:
        arr = np.array(devs).reshape(n // tp, tp)
    return Mesh(arr, axis_names=tuple(axis_names))


def pool_submeshes(
    n_members: int,
    devices: Optional[Sequence] = None,
    tp: Optional[int] = None,
) -> list[Mesh]:
    """Static partition of the slice into one sub-mesh per pool member —
    the SURVEY §7 hard-part-1 design: each member's generate runs on its own
    chips and the host scheduler overlaps members (models/runtime.py).

    Contiguous device ranges keep each member's tp collectives on
    neighboring ICI links. With fewer devices than members, members share
    meshes round-robin (degenerates to the single-chip case at n=1).
    """
    devs = list(devices) if devices is not None else jax.devices()
    per = max(1, len(devs) // n_members)
    meshes = []
    for i in range(n_members):
        lo = (i * per) % len(devs)
        sub = devs[lo:lo + per] or devs[:per]
        t = tp or len(sub)
        t = _largest_tp_divisor(len(sub), t)
        arr = np.array(sub).reshape(len(sub) // t, t)
        meshes.append(Mesh(arr, axis_names=("dp", "tp")))
    return meshes


def replica_device_groups(
    n_replicas: int,
    devices: Optional[Sequence] = None,
) -> list[list]:
    """Static partition of the slice into one contiguous device group
    per REPLICA (ISSUE 10, serving/cluster.py): each group is then
    sub-partitioned per pool member by :func:`pool_submeshes`, so a
    2-replica 3-member pool on 8 chips is 2 × (4 chips → 3 sub-meshes).
    Contiguity keeps every replica's intra-member tp collectives on
    neighboring ICI links and replicas fully independent (no cross-
    replica collective exists — the router is the only coupling). With
    fewer devices than replicas, replicas share devices round-robin
    (degenerates to everyone-on-one-chip at n=1 — the CPU test case)."""
    devs = list(devices) if devices is not None else jax.devices()
    per = max(1, len(devs) // n_replicas)
    groups = []
    for i in range(n_replicas):
        lo = (i * per) % len(devs)
        sub = devs[lo:lo + per] or devs[:per]
        groups.append(sub)
    return groups


def host_layout(n_hosts: int, chips_per_host: int,
                tp: Optional[int] = None,
                fsdp: Optional[int] = None) -> dict:
    """Canonical dp/fsdp/tp sizing for an ``n_hosts x chips_per_host``
    deployment (ISSUE 12; SNIPPETS.md [2]/[3], PAPERS.md "Scalable
    Training of Language Models using JAX pjit and TPUv4"): tp stays
    INSIDE a host (its collectives ride ICI every step), fsdp spans the
    hosts (its all-gathers amortize over a layer, so DCN-class links
    carry them), and dp takes whatever remains. Returns
    ``{"dp", "fsdp", "tp", "n_hosts", "chips_per_host", "total"}``
    with ``dp * fsdp * tp == n_hosts * chips_per_host``."""
    n_hosts = max(1, int(n_hosts))
    chips_per_host = max(1, int(chips_per_host))
    total = n_hosts * chips_per_host
    tp = min(chips_per_host, tp or chips_per_host)
    while chips_per_host % tp:
        tp -= 1
    fsdp = fsdp if fsdp is not None else n_hosts
    fsdp = max(1, min(fsdp, total // tp))
    while (total // tp) % fsdp:
        fsdp -= 1
    dp = total // (tp * fsdp)
    return {"dp": dp, "fsdp": fsdp, "tp": tp, "n_hosts": n_hosts,
            "chips_per_host": chips_per_host, "total": total}


def make_host_mesh(n_hosts: int, chips_per_host: int,
                   tp: Optional[int] = None,
                   fsdp: Optional[int] = None,
                   devices: Optional[Sequence] = None) -> Mesh:
    """A ("dp", "fsdp", "tp") mesh laid out HOST-MAJOR per
    :func:`host_layout`: the fastest-varying axis (tp) walks one host's
    chips, so device i*chips_per_host..(i+1)*chips_per_host-1 — host
    i's local devices in a multi-process jax.devices() ordering — hold
    whole tp groups, and dp/fsdp boundaries land on host boundaries
    wherever the layout allows. SPMD jobs (training, dryruns) shard
    over it; the serving plane stays host-local by design
    (runtime.py) and sizes itself with :func:`pool_sizing`'s ``hosts``
    dimension instead."""
    lay = host_layout(n_hosts, chips_per_host, tp=tp, fsdp=fsdp)
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < lay["total"]:
        raise ValueError(
            f"host mesh needs {lay['total']} devices "
            f"({n_hosts} hosts x {chips_per_host}); only "
            f"{len(devs)} visible")
    arr = np.array(devs[:lay["total"]]).reshape(
        lay["dp"], lay["fsdp"], lay["tp"])
    return Mesh(arr, axis_names=("dp", "fsdp", "tp"))


V5E_HBM_BYTES = 16 * 1024 ** 3          # 16 GiB per v5e chip (public spec)
POOL_TAIL_RESERVE = 1.25 * 1024 ** 3    # activations + compiled programs +
                                        # grammar tables + fragmentation


def device_hbm_limit(device) -> int:
    """Best-effort memory capacity of one jax device, in bytes: the live
    runtime's ``memory_stats()`` limit when the backend exposes it (TPU
    and GPU do), the public v5e spec as the TPU fallback, 0 for hosts
    that report nothing (CPU) — callers treat 0 as "no budget known"
    rather than inventing one (infra/resources.py headroom gauges)."""
    try:
        stats = device.memory_stats()
    except Exception:                     # noqa: BLE001 — optional API
        stats = None
    if stats:
        limit = int(stats.get("bytes_limit")
                    or stats.get("bytes_reservable_limit") or 0)
        if limit > 0:
            return limit
    return (V5E_HBM_BYTES
            if getattr(device, "platform", "") == "tpu" else 0)


def pool_sizing(pool: Sequence[str], n_devices: int = 8,
                hbm_per_chip: int = V5E_HBM_BYTES,
                dtype_bytes: int = 2,
                host_kv_mb: int = 0,
                disk_kv_gb: float = 0.0,
                page: int = 128,
                replicas: int = 1,
                disaggregate: bool = False,
                hosts: int = 1,
                quantize_weights: bool = False,
                quantize_kv: bool = False,
                fleet_min: int = 1,
                fleet_max: int = 0,
                trainer_chips: int = 0,
                capture_events_per_s: float = 0.0,
                capture_mb: float = 256.0) -> dict:
    """Explicit HBM budget for a model pool on a v5e sub-mesh partition
    (VERDICT r4 item 4): per member — chips (= recommended_tp), bf16
    weight bytes per chip, the page-pool bytes left after the tail
    reserve, and how many resident KV tokens that pool holds. The
    placement is the SURVEY §7 hard-part-1 design: a static partition of
    the slice, one contiguous tp sub-mesh per member.

    With tiered KV (ISSUE 7, serving/kvtier.py) the HBM figure stops
    being the capacity ceiling: ``host_kv_mb`` (per member, the
    ``--host-kv-mb`` flag) and ``disk_kv_gb`` (the ``--disk-kv-dir``
    store's budget; 0 = unbounded when enabled elsewhere) extend each
    member with host/disk tier rows — the ``tiers`` block reports
    resident HBM pages beside hibernation and durable-prefix capacity in
    tokens, so ``--plan`` output matches what the serving path actually
    holds. Host/disk copies are UNSHARDED (full KV bytes per token),
    hence the tp=1 byte rate in those rows.

    With ``replicas`` > 1 (ISSUE 10, serving/cluster.py) the plan grows
    a ``replica_tiers`` section matching the disaggregated topology:
    the slice splits into ``replicas`` contiguous device groups
    (``replica_device_groups``), each holding the WHOLE pool, and —
    under ``disaggregate`` — the first ``max(1, replicas // 2)`` groups
    form the prefill tier, the rest the decode tier (the cluster
    builder's split). Per role: replica count, device count, HBM
    budget, and resident-session capacity (sessions of one context
    window each, summed over the role's replicas; prefill replicas hold
    sessions only transiently — pages hibernate out at handoff — so
    steady-state resident capacity is attributed to the decode tier).

    With ``hosts`` > 1 (ISSUE 12, serving/fabric/) the plan answers
    "N hosts x M chips" instead of assuming one device set:
    ``n_devices`` becomes PER-HOST chips, replicas stay HOST-LOCAL
    (serving never spans a collective across hosts — the fabric wire is
    the only cross-host coupling), and a ``hosts`` block reports
    replicas-per-host packing, the host count the topology needs, and
    the canonical dp/fsdp/tp layout (:func:`host_layout`) an SPMD job
    of the same footprint would shard over.

    Returns {"members": [...], "chips_used", "fits", "hbm_per_chip"};
    ``fits`` is False when the pool needs more chips than the slice has
    or any member's weights alone exceed its chips' HBM.
    """
    from quoracle_tpu.models.config import get_model_config
    members, used, fits = [], 0, True
    # Quantized serving (ISSUE 13): plan at the byte rates the ladder
    # actually pays — int8 weights are 1 byte/param; int8 KV is 1
    # byte/elem plus 8 bytes per (token, kv-head) of fp32 K+V scales
    # (models/quant.py). Host/disk tier token rates quantize too: the
    # scales travel WITH the pages through every tier.
    w_byte = 1 if quantize_weights else dtype_bytes
    for spec in pool:
        cfg = get_model_config(spec)
        tp = _largest_tp_divisor(cfg.n_kv_heads,
                                 max(1, cfg.recommended_tp))
        weights = cfg.n_params * w_byte
        w_per_chip = weights / tp
        page_pool = hbm_per_chip - w_per_chip - POOL_TAIL_RESERVE
        if quantize_kv:
            kv_tok = (cfg.kv_bytes_per_token(tp, 1)
                      + cfg.n_layers * max(1, cfg.n_kv_heads // tp) * 8)
        else:
            kv_tok = cfg.kv_bytes_per_token(tp, dtype_bytes)
        resident = int(page_pool // kv_tok) if page_pool > 0 else 0
        m_fits = page_pool > 0
        fits = fits and m_fits
        used += tp
        # host/disk tiers hold full (unsharded) KV bytes per token
        if quantize_kv:
            kv_tok_host = (cfg.kv_bytes_per_token(1, 1)
                           + cfg.n_layers * cfg.n_kv_heads * 8)
        else:
            kv_tok_host = cfg.kv_bytes_per_token(1, dtype_bytes)
        host_tokens = int(host_kv_mb * (1 << 20) // kv_tok_host) \
            if host_kv_mb else 0
        disk_tokens = int(disk_kv_gb * (1 << 30) // kv_tok_host) \
            if disk_kv_gb else 0
        members.append({
            "model": cfg.name, "tp": tp, "chips": tp,
            "params_b": round(cfg.n_params / 1e9, 2),
            "weights_gb_per_chip": round(w_per_chip / 1024 ** 3, 2),
            "page_pool_gb_per_chip": round(max(0.0, page_pool) / 1024 ** 3,
                                           2),
            "kv_bytes_per_token_per_chip": kv_tok,
            "weights_dtype": "int8" if quantize_weights else "bf16",
            "kv_dtype": "int8+scales" if quantize_kv else "bf16",
            "resident_kv_tokens": resident,
            "tiers": {
                "hbm_pages": resident // page,
                "hbm_tokens": resident,
                "host_kv_mb": host_kv_mb,
                "host_kv_tokens": host_tokens,
                # disk store has no built-in budget: 0 here means
                # "no explicit cap given", not "no disk tier"
                "disk_kv_tokens": disk_tokens,
            },
            "fits": m_fits,
        })
    hosts = max(1, int(hosts))
    total_devices = hosts * n_devices
    fits = fits and used * max(1, replicas) <= total_devices
    out = {"members": members, "chips_used": used * max(1, replicas),
           "n_devices": n_devices, "fits": fits,
           "hbm_per_chip_gb": round(hbm_per_chip / 1024 ** 3, 2),
           "tail_reserve_gb": round(POOL_TAIL_RESERVE / 1024 ** 3, 2),
           "host_kv_mb_per_member": host_kv_mb}
    if hosts > 1:
        # replicas are host-local: a replica's engines never issue a
        # cross-host collective, so packing is per-host chips / chips
        # per replica, and the host count the topology needs follows
        per_host = n_devices // used if used else 0
        hosts_needed = (-(-max(1, replicas) // per_host) if per_host
                        else hosts + 1)
        fits = fits and per_host >= 1 and hosts_needed <= hosts
        out["fits"] = fits
        out["hosts"] = {
            "hosts": hosts,
            "chips_per_host": n_devices,
            "total_chips": total_devices,
            "replicas_per_host": per_host,
            "hosts_needed": hosts_needed,
            "fits": per_host >= 1 and hosts_needed <= hosts,
            "layout": host_layout(hosts, n_devices,
                                  tp=max((m["tp"] for m in members),
                                         default=1)),
        }
    if replicas > 1:
        out["replica_tiers"] = _replica_tiers(
            list(pool), members, used, total_devices, replicas,
            disaggregate, hbm_per_chip, host_kv_mb,
            quantize_kv=quantize_kv)
        if fleet_max:
            # Elastic fleet (ISSUE 14, serving/fleet.py): the capacity
            # ENVELOPE the autoscaler moves within — serving-tier
            # resident sessions at the min and max bounds, and whether
            # the slice can even hold the max (a fleet that cannot
            # reach --fleet-max is a misconfiguration the plan should
            # say out loud). New replicas share the default device set
            # until the next reboot repartitions, so devices_at_max is
            # the honest post-reboot figure.
            rt = out["replica_tiers"]
            serving = rt.get("decode") or rt.get("unified")
            n_reps = max(1, serving["replicas"])
            per_sessions = serving["resident_sessions"] // n_reps
            per_host_s = serving["host_tier_sessions"] // n_reps
            n_prefill = rt.get("prefill", {}).get("replicas", 0)
            devices_at_max = (n_prefill + fleet_max) * used
            out["fleet"] = {
                "min_replicas": fleet_min,
                "max_replicas": fleet_max,
                "serving_role": serving["role"],
                "resident_sessions_min": per_sessions * fleet_min,
                "resident_sessions_max": per_sessions * fleet_max,
                "host_tier_sessions_min": per_host_s * fleet_min,
                "host_tier_sessions_max": per_host_s * fleet_max,
                "devices_at_max": devices_at_max,
                "fits_at_max": devices_at_max <= total_devices,
            }
    if trainer_chips:
        out["trainer"] = _trainer_sizing(list(pool), trainer_chips,
                                         capture_events_per_s,
                                         capture_mb)
    return out


# Nominal crc-framed JSON bytes per captured spec round: CTX_TAIL token
# ids (~6 chars each serialized) plus proposal/verified arrays and the
# fixed fields — measured ~3.5 KiB on the CPU smoke corpus, planned at
# 4 KiB so the retention figure errs conservative.
CAPTURE_RECORD_BYTES = 4096


def _trainer_sizing(pool: list, trainer_chips: int,
                    capture_events_per_s: float,
                    capture_mb: float) -> dict:
    """The serving-flywheel block of a --plan (ISSUE 19): the
    distillation job's submesh (pure data-parallel over the draft — the
    draft is small enough that tp=1 always fits, which is why it IS the
    draft), the capture store's ingest rate vs its disk budget (how
    many days of traffic the ``--capture-mb`` budget retains before
    oldest-first eviction), and the checkpoint footprint (fp32 params
    plus the two adamw moment trees)."""
    from quoracle_tpu.models.config import get_model_config
    # the flywheel trains the DRAFT: size against the pool's smallest
    # member, which is the one a speculator would propose with
    cfgs = [get_model_config(s) for s in pool]
    draft = min(cfgs, key=lambda c: c.n_params)
    layout = host_layout(1, trainer_chips, tp=1)
    ckpt_bytes = draft.n_params * 4 * 3
    daily_bytes = capture_events_per_s * CAPTURE_RECORD_BYTES * 86400
    budget_bytes = capture_mb * (1 << 20)
    return {
        "draft_model": draft.name,
        "chips": trainer_chips,
        "layout": layout,
        "batch_rows_per_step_min": layout["dp"],
        "checkpoint_gb": round(ckpt_bytes / 1024 ** 3, 3),
        "capture": {
            "events_per_s": capture_events_per_s,
            "record_bytes_nominal": CAPTURE_RECORD_BYTES,
            "mb_per_day": round(daily_bytes / (1 << 20), 1),
            "budget_mb": capture_mb,
            "retention_days": (round(budget_bytes / daily_bytes, 2)
                               if daily_bytes else None),
        },
    }


def _replica_tiers(pool: list, members: list, chips_per_replica: int,
                   n_devices: int, replicas: int, disaggregate: bool,
                   hbm_per_chip: int, host_kv_mb: int,
                   quantize_kv: bool = False) -> dict:
    """The per-role capacity block of a multi-replica --plan (ISSUE 10
    satellite). Session capacity is denominated in resident sessions of
    ONE full context window per member (the conservative agent-serving
    unit); the host tier extends the decode tier's figure exactly as in
    the single-replica tiers rows."""
    n_prefill = max(1, replicas // 2) if disaggregate else 0
    n_decode = replicas - n_prefill

    def _tier(name: str, n_reps: int, resident: bool) -> dict:
        from quoracle_tpu.models.config import get_model_config
        sessions = 0
        host_sessions = 0
        for spec, m in zip(pool, members):
            cfg = get_model_config(spec)
            window = max(1, cfg.context_window)
            sessions += m["resident_kv_tokens"] // window
            if host_kv_mb:
                kv_tok_host = (
                    cfg.kv_bytes_per_token(1, 1)
                    + cfg.n_layers * cfg.n_kv_heads * 8
                    if quantize_kv else cfg.kv_bytes_per_token(1, 2))
                host_sessions += int(host_kv_mb * (1 << 20)
                                     // kv_tok_host) // window
        return {
            "role": name,
            "replicas": n_reps,
            "devices": n_reps * chips_per_replica,
            "hbm_budget_gb": round(
                n_reps * chips_per_replica * hbm_per_chip / 1024 ** 3,
                2),
            # prefill replicas park nothing: sessions hibernate out at
            # handoff, so steady-state residency is a decode-tier number
            "resident_sessions": (sessions * n_reps if resident else 0),
            "host_tier_sessions": (host_sessions * n_reps
                                   if resident else 0),
        }

    tiers = {}
    if disaggregate:
        tiers["prefill"] = _tier("prefill", n_prefill, resident=False)
        tiers["decode"] = _tier("decode", n_decode, resident=True)
    else:
        tiers["unified"] = _tier("unified", replicas, resident=True)
    tiers["total_devices_needed"] = replicas * chips_per_replica
    tiers["fits"] = replicas * chips_per_replica <= n_devices
    tiers["disaggregate"] = disaggregate
    return tiers


def _largest_tp_divisor(n_kv_heads: int, tp_size: int) -> int:
    d = min(n_kv_heads, tp_size)
    while n_kv_heads % d or tp_size % d:
        d -= 1
    return d


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec pytree matching transformer.init_params' structure.

    Megatron-style: qkv/gate/up shard the OUTPUT feature dim (heads / ffn
    columns), wo/down shard the INPUT dim — the pre-matmul activations stay
    replicated-by-row and GSPMD inserts one psum per block. Embedding shards
    the vocab axis (the gather and the logit matmul both parallelize).
    """
    specs = {
        "embed": P("tp", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(None),
    }
    if cfg.attn_bias:
        # biases follow their projection's output sharding
        specs["layers"]["bq"] = P(None, "tp")
        specs["layers"]["bk"] = P(None, "tp")
        specs["layers"]["bv"] = P(None, "tp")
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def cache_spec(cfg: ModelConfig, mesh: Mesh) -> P:
    """KV cache [L, B, S, n_kv, hd]: batch on dp, kv heads on tp (when they
    divide; MQA/MHA mismatches fall back to replicated kv heads). On an
    sp-capable mesh the SEQUENCE axis shards over sp — ring-prefilled
    prompts never materialize whole on one chip, and decode's attention
    contraction over S becomes a GSPMD psum across the ring."""
    tp_size = mesh.shape.get("tp", 1)
    kv_axis = "tp" if cfg.n_kv_heads % tp_size == 0 else None
    sp_axis = "sp" if mesh.shape.get("sp", 1) > 1 else None
    return P(None, "dp", sp_axis, kv_axis, None)


def data_spec() -> P:
    """Token batches [B, T]: rows ride dp."""
    return P("dp", None)


def shard_params(params: dict, mesh: Mesh, cfg: ModelConfig) -> dict:
    """Place a params pytree onto the mesh per param_specs."""
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _main(argv=None) -> int:
    """``python -m quoracle_tpu.parallel.mesh --plan``: print the pool's
    HBM/capacity plan as JSON — including the replica-tier section when
    ``--replicas`` > 1, so capacity planning matches the disaggregated
    topology (ISSUE 10 satellite)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="quoracle_tpu.parallel.mesh")
    ap.add_argument("--plan", action="store_true",
                    help="print the pool_sizing plan as JSON")
    ap.add_argument("--pool", default=None,
                    help="comma-separated model specs (default: the "
                         "bench pool)")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--host-kv-mb", dest="host_kv_mb", type=int,
                    default=0)
    ap.add_argument("--disk-kv-gb", dest="disk_kv_gb", type=float,
                    default=0.0)
    ap.add_argument("--replicas", type=int, default=1,
                    help="data-parallel replicas of the whole pool "
                         "(serving/cluster.py)")
    ap.add_argument("--disaggregate", action="store_true",
                    help="split replicas into prefill/decode tiers")
    ap.add_argument("--hosts", type=int, default=1,
                    help="cross-host fabric topology (ISSUE 12): plan "
                         "over N hosts x --devices chips each; "
                         "replicas stay host-local, the wire is the "
                         "only cross-host coupling")
    ap.add_argument("--fleet-min", dest="fleet_min", type=int,
                    default=1,
                    help="elastic fleet (ISSUE 14): autoscaler lower "
                         "bound for the serving tier")
    ap.add_argument("--fleet-max", dest="fleet_max", type=int,
                    default=0,
                    help="elastic fleet: plan the capacity envelope "
                         "the autoscaler moves within (0 = static)")
    ap.add_argument("--trainer-chips", dest="trainer_chips", type=int,
                    default=0,
                    help="serving flywheel (ISSUE 19): size the draft "
                         "distillation job's data-parallel submesh "
                         "(0 = no trainer section)")
    ap.add_argument("--capture-events-per-s", dest="capture_events_per_s",
                    type=float, default=0.0,
                    help="flywheel capture ingest rate for the "
                         "retention estimate")
    ap.add_argument("--capture-mb", dest="capture_mb", type=float,
                    default=256.0,
                    help="flywheel capture store disk budget "
                         "(training/capture.py oldest-first eviction)")
    ap.add_argument("--quantize-weights", dest="quantize_weights",
                    action="store_true",
                    help="plan at the int8 weight byte rate (ISSUE 13)")
    ap.add_argument("--quantize-kv", dest="quantize_kv",
                    action="store_true",
                    help="plan at the int8+scales KV byte rate — "
                         "resident/host/disk token figures ~double")
    args = ap.parse_args(argv)
    if args.pool:
        pool = args.pool.split(",")
    else:
        from quoracle_tpu.models.config import BENCH_POOL
        pool = list(BENCH_POOL)
    plan = pool_sizing(pool, args.devices, host_kv_mb=args.host_kv_mb,
                       disk_kv_gb=args.disk_kv_gb,
                       replicas=args.replicas,
                       disaggregate=args.disaggregate,
                       hosts=args.hosts,
                       quantize_weights=args.quantize_weights,
                       quantize_kv=args.quantize_kv,
                       fleet_min=args.fleet_min,
                       fleet_max=args.fleet_max,
                       trainer_chips=args.trainer_chips,
                       capture_events_per_s=args.capture_events_per_s,
                       capture_mb=args.capture_mb)
    print(json.dumps(plan, indent=2))
    return 0 if plan["fits"] else 1


if __name__ == "__main__":
    raise SystemExit(_main())
