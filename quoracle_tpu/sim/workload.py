"""Deterministic fleet-workload model (ISSUE 16 tentpole, part a).

A trace is a SORTED list of arrival events generated from a
:class:`WorkloadSpec` by pure seeded draws — the chaos plane's seeding
idiom (ARCHITECTURE §14): every draw is
``sha256(f"{seed}:{stream}:{n}")`` with ``n`` a per-stream counter, so
concurrent stream generation order cannot perturb the schedule and the
same spec reproduces the same bytes on any host. No wall clock, no
``random``, no process-salted ``hash()``.

Four composable stream families:

* **tenants** — Poisson-ish arrivals whose rate follows a diurnal
  intensity curve (inverse-transform exponential inter-arrivals against
  the instantaneous rate);
* **storms** — bounded burst windows multiplying one tenant's rate;
* **agent trees** — recursive spawn fan-outs (the source app's spawn
  recursion): a root request spawns ``branching[d]`` children at depth
  ``d``, each carrying that depth's consensus K;
* **long tail** — O(100k) virtual sessions, most touched once and then
  hibernated, whose reactivation inter-arrivals are drawn from a
  heavy-tailed per-session rate so replay exercises the full
  HBM→host→disk→prefixd tier ladder.

Serialization is canonical (sorted keys, no whitespace, ints only in
event rows), so *byte*-identical traces under the same seed is a
checkable contract, not an accident of dict ordering.

The ``bench_*`` helpers at the bottom are the single home for the
prompt mixes bench.py configs 11/20/22 drive — previously duplicated
hand-rolled loops, now sourced from a simulator trace.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Optional, Sequence

# priority classes as they appear in traces (stable strings, mapped to
# serving/qos.Priority only at replay time)
CLASSES = ("interactive", "agent", "batch")

_U64 = float(1 << 64)


def draw(seed: int, stream: str, n: int) -> float:
    """Uniform [0, 1) from sha256(seed:stream:n) — the chaos plane's
    seeding idiom, shared verbatim so one contract covers both planes."""
    digest = hashlib.sha256(f"{seed}:{stream}:{n}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / _U64


def draw_exp(seed: int, stream: str, n: int, mean: float) -> float:
    """Exponential with the given mean (inverse transform)."""
    u = draw(seed, stream, n)
    return -mean * math.log(1.0 - u)


def draw_int(seed: int, stream: str, n: int, lo: int, hi: int) -> int:
    """Integer in [lo, hi] inclusive."""
    if hi <= lo:
        return lo
    return lo + int(draw(seed, stream, n) * (hi - lo + 1))


# ---------------------------------------------------------------------------
# Spec
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant population with a diurnal intensity curve."""

    name: str
    rate_per_s: float                     # mean arrivals/s at intensity 1
    diurnal_amplitude: float = 0.0        # 0 = flat, 1 = full swing
    peak_hour: float = 12.0               # virtual hour of peak intensity
    mix: tuple = (("interactive", 1.0),)  # ((class, weight), ...)
    prompt_tokens: tuple = (32, 96)       # [lo, hi] drawn per event
    max_new_tokens: tuple = (8, 32)


@dataclasses.dataclass(frozen=True)
class StormSpec:
    """A burst window multiplying one tenant's arrival rate."""

    tenant: str
    t_start_ms: int
    duration_ms: int
    multiplier: float = 8.0


@dataclasses.dataclass(frozen=True)
class AgentTreeSpec:
    """Recursive spawn fan-out: roots arrive on a fixed cadence; a node
    at depth d spawns ``branching[d]`` children after a drawn delay,
    each carrying ``consensus_k[d+1]`` (the per-depth consensus K)."""

    n_roots: int
    root_every_ms: int
    branching: tuple = (3, 2)             # children per node per depth
    consensus_k: tuple = (3, 2, 1)        # K at depth 0, 1, 2, ...
    spawn_delay_ms: tuple = (20, 200)     # [lo, hi] child delay
    tenant: str = "agents"
    prompt_tokens: tuple = (48, 128)
    max_new_tokens: tuple = (16, 48)


@dataclasses.dataclass(frozen=True)
class LongTailSpec:
    """O(100k) virtual sessions: each is established once, then
    reactivates ``~Poisson(mean_reactivations × pareto(alpha))`` times —
    a heavy tail where most sessions hibernate forever and a few stay
    hot, which is exactly the population the tier ladder exists for."""

    n_sessions: int
    mean_reactivations: float = 0.3
    heavy_tail_alpha: float = 1.3         # pareto shape for per-session rate
    establish_frac: float = 0.5           # establishes land in this first
                                          # fraction of the horizon
    tenant: str = "longtail"
    prompt_tokens: tuple = (24, 64)
    max_new_tokens: tuple = (4, 16)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    seed: int
    horizon_ms: int
    tenants: tuple = ()
    storms: tuple = ()
    agent_trees: tuple = ()
    longtail: Optional[LongTailSpec] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadSpec":
        def _tup(v):
            return tuple(tuple(x) if isinstance(x, list) else x
                         for x in v)
        lt = d.get("longtail")
        return cls(
            seed=int(d["seed"]), horizon_ms=int(d["horizon_ms"]),
            tenants=tuple(TenantSpec(**{**t,
                                        "mix": _tup(t.get("mix", ())),
                                        "prompt_tokens": tuple(
                                            t.get("prompt_tokens",
                                                  (32, 96))),
                                        "max_new_tokens": tuple(
                                            t.get("max_new_tokens",
                                                  (8, 32)))})
                          for t in d.get("tenants", ())),
            storms=tuple(StormSpec(**s) for s in d.get("storms", ())),
            agent_trees=tuple(
                AgentTreeSpec(**{**a,
                                 "branching": tuple(a.get("branching",
                                                          (3, 2))),
                                 "consensus_k": tuple(
                                     a.get("consensus_k", (3, 2, 1))),
                                 "spawn_delay_ms": tuple(
                                     a.get("spawn_delay_ms", (20, 200))),
                                 "prompt_tokens": tuple(
                                     a.get("prompt_tokens", (48, 128))),
                                 "max_new_tokens": tuple(
                                     a.get("max_new_tokens", (16, 48)))})
                for a in d.get("agent_trees", ())),
            longtail=(None if lt is None else LongTailSpec(
                **{**lt,
                   "prompt_tokens": tuple(lt.get("prompt_tokens",
                                                 (24, 64))),
                   "max_new_tokens": tuple(lt.get("max_new_tokens",
                                                  (4, 16)))})),
        )


# ---------------------------------------------------------------------------
# Events & trace
# ---------------------------------------------------------------------------

# default per-class SLO deadline attached to every event (ms of modeled
# TTFT the class tolerates before the row is deadline-shed)
CLASS_DEADLINE_MS = {"interactive": 1_500, "agent": 6_000, "batch": 0}


@dataclasses.dataclass(frozen=True, slots=True)
class SimEvent:
    """One arrival. ``eid`` is stable across runs (stream-derived, not
    positional), ``depth``/``consensus_k`` carry agent-tree structure,
    and every numeric field is an int so serialization is canonical.
    Slots: a long-tail trace holds O(100k) of these."""

    eid: str
    t_ms: int
    stream: str                           # generator family
    session: str
    tenant: str
    cls: str                              # one of CLASSES
    prompt_tokens: int
    max_new_tokens: int
    deadline_ms: int                      # 0 = none
    depth: int = 0
    consensus_k: int = 1

    def as_row(self) -> list:
        return [self.eid, self.t_ms, self.stream, self.session,
                self.tenant, self.cls, self.prompt_tokens,
                self.max_new_tokens, self.deadline_ms, self.depth,
                self.consensus_k]

    @classmethod
    def from_row(cls, r: Sequence) -> "SimEvent":
        return cls(eid=r[0], t_ms=int(r[1]), stream=r[2], session=r[3],
                   tenant=r[4], cls=r[5], prompt_tokens=int(r[6]),
                   max_new_tokens=int(r[7]), deadline_ms=int(r[8]),
                   depth=int(r[9]), consensus_k=int(r[10]))


class Trace:
    """A generated workload: spec + sorted events, serializable to
    canonical JSON (the reproducible artifact --sim-trace replays)."""

    VERSION = 1

    def __init__(self, spec: WorkloadSpec, events: list):
        self.spec = spec
        self.events = events

    def __len__(self) -> int:
        return len(self.events)

    def to_json(self) -> str:
        return json.dumps(
            {"version": self.VERSION, "spec": self.spec.as_dict(),
             "events": [e.as_row() for e in self.events]},
            sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]

    @classmethod
    def from_json(cls, text: str) -> "Trace":
        d = json.loads(text)
        if d.get("version") != cls.VERSION:
            raise ValueError(
                f"unsupported trace version {d.get('version')!r}")
        return cls(WorkloadSpec.from_dict(d["spec"]),
                   [SimEvent.from_row(r) for r in d["events"]])

    @classmethod
    def from_file(cls, path: str) -> "Trace":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(f.read())

    def window_mix(self, t0_ms: int, t1_ms: int) -> dict:
        """Per-class offered arrival rate (events/s) in [t0, t1) — the
        traffic-mix prior the forecast seam feeds FleetSignals (shadow
        mode: the policy records it, never acts on it yet)."""
        span_s = max(1e-9, (t1_ms - t0_ms) / 1000.0)
        counts = {c: 0 for c in CLASSES}
        for e in self.events:             # events are sorted by t_ms
            if e.t_ms >= t1_ms:
                break
            if e.t_ms >= t0_ms:
                counts[e.cls] += 1
        return {c: round(n / span_s, 4) for c, n in counts.items()}

    def stats(self) -> dict:
        by_stream: dict = {}
        by_cls = {c: 0 for c in CLASSES}
        sessions = set()
        for e in self.events:
            by_stream[e.stream] = by_stream.get(e.stream, 0) + 1
            by_cls[e.cls] += 1
            sessions.add(e.session)
        return {"events": len(self.events), "sessions": len(sessions),
                "by_stream": by_stream, "by_class": by_cls,
                "horizon_ms": self.spec.horizon_ms,
                "seed": self.spec.seed, "digest": self.digest()}


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------


def _intensity(t_ms: int, spec: TenantSpec) -> float:
    """Diurnal curve: 1 + amplitude·cos(2π·(hour − peak)/24), floored at
    a 5% trickle so inter-arrival means stay finite."""
    if spec.diurnal_amplitude <= 0.0:
        return 1.0
    hour = (t_ms / 3_600_000.0) % 24.0
    factor = 1.0 + spec.diurnal_amplitude * math.cos(
        2.0 * math.pi * (hour - spec.peak_hour) / 24.0)
    return max(0.05, factor)


def _storm_multiplier(t_ms: int, tenant: str, storms) -> float:
    m = 1.0
    for s in storms:
        if (s.tenant == tenant and s.t_start_ms <= t_ms
                < s.t_start_ms + s.duration_ms):
            m *= s.multiplier
    return m


def _pick_class(u: float, mix) -> str:
    total = sum(w for _, w in mix)
    acc = 0.0
    for cls, w in mix:
        acc += w / total
        if u < acc:
            return cls
    return mix[-1][0]


def _event(seed: int, stream: str, n: int, t_ms: int, session: str,
           tenant: str, cls: str, ptok: tuple, ntok: tuple,
           depth: int = 0, k: int = 1) -> SimEvent:
    return SimEvent(
        eid=f"{stream}/{n}", t_ms=int(t_ms), stream=stream,
        session=session, tenant=tenant, cls=cls,
        prompt_tokens=draw_int(seed, f"{stream}:ptok", n, *ptok),
        max_new_tokens=draw_int(seed, f"{stream}:ntok", n, *ntok),
        deadline_ms=CLASS_DEADLINE_MS.get(cls, 0), depth=depth,
        consensus_k=k)


def tree_id_of(e) -> str:
    """Agent-tree lineage id for a trace event (ISSUE 20 satellite):
    tree sessions are named ``tree{idx}-r{r}`` at the root and
    ``{parent}.{c}`` down the spawn chain, so the root segment before
    the first dot IS the tree id. Non-tree events (any stream other
    than ``tree:*``) carry no lineage — empty string."""
    if not getattr(e, "stream", "").startswith("tree:"):
        return ""
    return e.session.split(".", 1)[0]


def _gen_tenant(spec: WorkloadSpec, t: TenantSpec, out: list) -> None:
    stream = f"tenant:{t.name}"
    n = 0
    t_ms = 0.0
    while True:
        rate = (t.rate_per_s * _intensity(int(t_ms), t)
                * _storm_multiplier(int(t_ms), t.name, spec.storms))
        t_ms += 1000.0 * draw_exp(spec.seed, stream, n, 1.0 / rate)
        if t_ms >= spec.horizon_ms:
            break
        cls = _pick_class(draw(spec.seed, f"{stream}:cls", n), t.mix)
        out.append(_event(
            spec.seed, stream, n, t_ms,
            session=f"{t.name}-s{n}", tenant=t.name, cls=cls,
            ptok=t.prompt_tokens, ntok=t.max_new_tokens))
        n += 1


def _gen_tree(spec: WorkloadSpec, idx: int, a: AgentTreeSpec,
              out: list) -> None:
    stream = f"tree:{idx}"
    n = 0

    def k_at(depth: int) -> int:
        if not a.consensus_k:
            return 1
        return a.consensus_k[min(depth, len(a.consensus_k) - 1)]

    def spawn(t_ms: float, depth: int, session: str) -> None:
        nonlocal n
        if t_ms >= spec.horizon_ms:
            return
        out.append(_event(
            spec.seed, stream, n, t_ms, session=session,
            tenant=a.tenant, cls="agent", ptok=a.prompt_tokens,
            ntok=a.max_new_tokens, depth=depth, k=k_at(depth)))
        my_n = n
        n += 1
        if depth >= len(a.branching):
            return
        for c in range(a.branching[depth]):
            delay = draw_int(spec.seed, f"{stream}:delay", my_n * 16 + c,
                             *a.spawn_delay_ms)
            spawn(t_ms + delay, depth + 1, f"{session}.{c}")

    for r in range(a.n_roots):
        jitter = draw_int(spec.seed, f"{stream}:root", r, 0,
                          max(1, a.root_every_ms // 4))
        spawn(r * a.root_every_ms + jitter, 0, f"tree{idx}-r{r}")


def _gen_longtail(spec: WorkloadSpec, lt: LongTailSpec,
                  out: list) -> None:
    stream = "longtail"
    n = 0
    est_span = max(1.0, lt.establish_frac * spec.horizon_ms)
    for s in range(lt.n_sessions):
        session = f"lt-{s}"
        # establish: one arrival somewhere in the first establish_frac
        # of the horizon (the session's birth into the tier ladder)
        t_ms = draw(spec.seed, f"{stream}:est", s) * est_span
        out.append(_event(
            spec.seed, stream, n, t_ms, session=session,
            tenant=lt.tenant, cls="batch", ptok=lt.prompt_tokens,
            ntok=lt.max_new_tokens))
        n += 1
        # heavy-tailed per-session reactivation rate: pareto(alpha)
        # multiplier, so most sessions never reactivate and a hot few
        # reactivate repeatedly
        u = draw(spec.seed, f"{stream}:rate", s)
        mult = (1.0 - u) ** (-1.0 / lt.heavy_tail_alpha)
        lam = lt.mean_reactivations * mult
        # deterministic touch count: floor + bernoulli on the fraction
        touches = int(lam) + (
            1 if draw(spec.seed, f"{stream}:frac", s) < (lam - int(lam))
            else 0)
        touches = min(touches, 64)        # a hot session, not a DoS
        remaining = spec.horizon_ms - t_ms
        if touches <= 0 or remaining <= 0:
            continue
        mean_gap = remaining / (touches + 1)
        for j in range(touches):
            t_ms += draw_exp(spec.seed, f"{stream}:gap",
                             s * 64 + j, mean_gap)
            if t_ms >= spec.horizon_ms:
                break
            out.append(_event(
                spec.seed, stream, n, t_ms, session=session,
                tenant=lt.tenant, cls="interactive",
                ptok=lt.prompt_tokens, ntok=lt.max_new_tokens))
            n += 1


def generate(spec: WorkloadSpec) -> Trace:
    """Expand a spec into a sorted, reproducible trace. Stream draws are
    independent, so the merge order below cannot perturb any stream's
    schedule; the final sort key includes the eid to keep simultaneous
    arrivals in a canonical order."""
    events: list = []
    for t in spec.tenants:
        _gen_tenant(spec, t, events)
    for i, a in enumerate(spec.agent_trees):
        _gen_tree(spec, i, a, events)
    if spec.longtail is not None:
        _gen_longtail(spec, spec.longtail, events)
    events.sort(key=lambda e: (e.t_ms, e.eid))
    return Trace(spec, events)


# ---------------------------------------------------------------------------
# Canonical specs (the tier-1 scenario traces + --sim-seed default)
# ---------------------------------------------------------------------------


def canonical_spec(name: str, seed: int = 0,
                   scale: float = 1.0) -> WorkloadSpec:
    """The four named workloads tier-1 replays (sim/gate.py). ``scale``
    shrinks/grows populations for bench smoke vs live runs."""
    if name == "diurnal_mix":
        return WorkloadSpec(
            seed=seed, horizon_ms=int(4 * 3_600_000 * scale),
            tenants=(
                TenantSpec("humans", rate_per_s=0.05,
                           diurnal_amplitude=0.8, peak_hour=2.0,
                           mix=(("interactive", 0.8), ("agent", 0.2))),
                TenantSpec("pipelines", rate_per_s=0.03,
                           diurnal_amplitude=0.4, peak_hour=14.0,
                           mix=(("batch", 0.9), ("agent", 0.1))),
            ))
    if name == "storm":
        horizon = int(1_200_000 * scale)
        return WorkloadSpec(
            seed=seed, horizon_ms=horizon,
            tenants=(
                TenantSpec("humans", rate_per_s=0.2,
                           mix=(("interactive", 1.0),)),
                TenantSpec("bulk", rate_per_s=0.3,
                           mix=(("batch", 1.0),)),
            ),
            storms=(StormSpec("bulk", t_start_ms=horizon // 3,
                              duration_ms=horizon // 3,
                              multiplier=12.0),))
    if name == "agent_tree":
        return WorkloadSpec(
            seed=seed, horizon_ms=600_000,
            agent_trees=(AgentTreeSpec(
                n_roots=max(1, int(24 * scale)), root_every_ms=20_000,
                branching=(3, 2), consensus_k=(3, 2, 1)),))
    if name == "longtail_ladder":
        return WorkloadSpec(
            seed=seed, horizon_ms=24 * 3_600_000,
            tenants=(TenantSpec("humans", rate_per_s=0.002,
                                mix=(("interactive", 1.0),)),),
            longtail=LongTailSpec(
                n_sessions=max(1, int(100_000 * scale))))
    raise ValueError(f"unknown canonical workload {name!r}; "
                     f"have diurnal_mix, storm, agent_tree, "
                     f"longtail_ladder")


CANONICAL = ("diurnal_mix", "storm", "agent_tree", "longtail_ladder")


# ---------------------------------------------------------------------------
# Bench mixes (satellite: the single home for configs 11/20/22 phases)
# ---------------------------------------------------------------------------


def bench_trace(kind: str, n: int, seed: int = 2026,
                spacing_ms: int = 1_000) -> Trace:
    """A tiny evenly-spaced single-stream trace: the simulator source
    for bench.py's fixed-count phases (each bench row is one event; the
    event's stream counter indexes its prompt text)."""
    cls = {"interactive": "interactive", "session": "agent",
           "batch": "batch"}[kind]
    spec = WorkloadSpec(seed=seed, horizon_ms=(n + 1) * spacing_ms)
    events = [_event(seed, f"bench:{kind}", i, i * spacing_ms,
                     session=f"bench-{kind}-{i}", tenant="bench",
                     cls=cls, ptok=(32, 96), ntok=(8, 32))
              for i in range(n)]
    return Trace(spec, events)


def bench_overload_mix(tasks: Sequence[str], n_interactive: int,
                       seed: int = 2026) -> dict:
    """Config 11's prompt mix: one long background BATCH prompt + the
    interactive turns, text indexed by the trace's event counters
    (formerly a hand-rolled loop in measure_qos_overload)."""
    tr = bench_trace("interactive", n_interactive, seed=seed)
    return {
        "batch_text": "background agent subtree task: "
                      + max(tasks, key=len),
        "interactive_texts": [
            f"[user turn {i}] {tasks[i % len(tasks)]}"
            for i, _ in enumerate(tr.events)],
        "trace": tr,
    }


def bench_fleet_mix(tasks: Sequence[str], n_interactive: int,
                    n_sessions: int, seed: int = 2026) -> dict:
    """Config 20's mixed traffic: short INTERACTIVE message rows + the
    sessioned AGENT working-state rows (formerly hand-rolled lists in
    measure_fleet), sourced from two tiny traces."""
    ti = bench_trace("interactive", n_interactive, seed=seed)
    ts = bench_trace("session", n_sessions, seed=seed + 1)
    return {
        "inter_msgs": [
            [{"role": "user",
              "content": f"[user {i}] {tasks[i % len(tasks)][:48]}"}]
            for i, _ in enumerate(ti.events)],
        "sess_msgs": [
            [{"role": "user",
              "content": f"[agent {i}] working state: "
                         + " ".join(tasks)[:384]}]
            for i, _ in enumerate(ts.events)],
        "traces": (ti, ts),
    }


def event_prompt_text(e: SimEvent) -> str:
    """The deterministic prompt text an engine-backed sampled replay
    submits for one event — a pure function of the event, so two
    replays of the same trace submit identical requests."""
    return (f"[sim {e.stream} {e.eid}] session {e.session} depth "
            f"{e.depth} k {e.consensus_k}: summarize the current plan "
            f"in one line.")
