"""Deterministic fleet-workload simulator (ISSUE 16).

Three parts, one contract:

* :mod:`quoracle_tpu.sim.workload` — a composable, seeded generator of
  traffic traces (diurnal tenant mixes, burst storms, recursive
  agent-tree fan-outs, a long-tail population of mostly-hibernated
  sessions). A trace is a reproducible artifact: pure
  ``sha256(seed:stream:n)`` draws, no wall clock, no ``random``,
  serializable to JSON byte-for-byte.
* :mod:`quoracle_tpu.sim.replay` — a compressed-time replay driver: a
  virtual clock walks the trace event by event against a deterministic
  capacity/tier-ladder model (optionally spot-checking a sampled subset
  through a real ClusterPlane/FabricPlane), recording every outcome
  into a ledger. Same trace, same ledger — bit-identical.
* :mod:`quoracle_tpu.sim.gate` — the chaos invariant catalog extended
  with workload-level postconditions (SLO attainment per class, goodput
  floor, no-silent-loss over the full ledger, hibernation-tier
  conservation, temp-0 spot equality), run as tier-1 scenarios.
* :mod:`quoracle_tpu.sim.calibrate` — measured-profile calibration
  (ISSUE 17): fit the CapacityModel's service-time parameters from a
  recorded chip-economics ledger (infra/costobs.py) and gate the fit on
  the calibrated replay reproducing the measured TTFT distribution.

The simulator is the serving plane's acceptance gate: every later
policy change (adaptive consensus gating, predictive autoscaling,
fabric burn-in) replays the same traces and must keep the same
invariants green.
"""

from quoracle_tpu.sim.workload import (  # noqa: F401
    SimEvent, Trace, WorkloadSpec, generate,
)
from quoracle_tpu.sim.replay import ReplayDriver, SIM  # noqa: F401
from quoracle_tpu.sim.gate import (  # noqa: F401
    SIM_SCENARIOS, run_sim_scenario,
)
# NOTE: the fit entry point stays at quoracle_tpu.sim.calibrate.calibrate —
# re-exporting a name equal to its own submodule would shadow the module
# object on the package.
from quoracle_tpu.sim.calibrate import (  # noqa: F401
    CalibrationReport, fit_capacity, record_profile, ttft_gate,
)
