"""Workload-level invariant gate (ISSUE 16 tentpole, part c).

The chaos catalog (chaos/invariants.py) proves the stack recovers from
injected faults; this module proves the stack would SERVE THE WORKLOAD
— the same ``InvariantResult`` currency, evaluated over a full replay
ledger instead of a storm window:

* ``slo_attainment``        — per priority class, the fraction of
  events served with modeled TTFT inside the class budget meets the
  attainment floor (a shed or deadline miss counts against the class);
* ``goodput_floor``         — delivered tokens per VIRTUAL second over
  the whole trace stay above the scenario floor;
* ``no_silent_loss_ledger`` — ledger rows and trace events match 1:1
  by event id, and every non-ok row carries a structured reason from
  the SAME closed prefix set the chaos plane enforces;
* ``tier_conservation``     — every virtual session the ladder ever
  saw is accounted resident/host/disk/prefixd/dropped (the
  hibernation-tier conservation law);
* ``ledger_deterministic``  — two replays of one trace serialize to
  byte-identical ledgers;
* ``sim_tree_conservation`` — agent-tree lineage ids in the ledger
  reconcile EXACTLY with the generated trace (ISSUE 20): per-tree row
  counts equal per-tree trace event counts, per-tree delivered-token
  sums equal the trace-side recomputation, and no row carries a tree
  id its trace event disagrees with;
* ``temp0_spot_equal``      — when a real plane rides along, the
  sampled temperature-0 texts from both replays are identical and
  every sampled failure is structured.

``SIM_SCENARIOS`` pins four canonical traces (diurnal mix, burst
storm, agent tree, long-tail ladder) to sized capacity models and
floors; ``run_sim_scenario`` replays one twice and evaluates the whole
catalog — the tier-1 acceptance gate every later serving-policy change
replays against (tests/test_sim.py, marker ``sim``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

from quoracle_tpu.chaos.invariants import (
    STRUCTURED_ERROR_PREFIXES, InvariantResult, conservation,
)
from quoracle_tpu.sim.replay import (
    SIM, TIERS, CapacityModel, ReplayDriver, ReplayLedger,
)
from quoracle_tpu.sim.workload import (
    Trace, canonical_spec, generate, tree_id_of,
)

logger = logging.getLogger(__name__)

MEMBER = "xla:tiny"

# ok-row reasons that are annotations, not failures
_OK_REASONS = ("", "cold_reprefill")


# -- the workload invariant catalog --------------------------------------

def slo_attainment(ledger: ReplayLedger, targets) -> list:
    """Per class: fraction of events with outcome ok AND modeled TTFT
    within the class budget; sheds and deadline misses count against
    the class (they ARE the SLO miss)."""
    out = []
    for cls, budget_ms, floor in targets:
        rows = [r for r in ledger.rows if r[2] == cls]
        if not rows:
            out.append(InvariantResult(
                f"sim_slo_{cls}", True, "no events of class"))
            continue
        hit = sum(1 for r in rows
                  if r[3] == "ok" and r[5] <= budget_ms * 1000)
        frac = hit / len(rows)
        out.append(InvariantResult(
            f"sim_slo_{cls}", frac >= floor,
            f"attained {hit}/{len(rows)} = {frac:.3f} "
            f"(budget {budget_ms}ms, floor {floor})"))
    return out


def goodput_floor(ledger: ReplayLedger, horizon_ms: int,
                  floor_tok_s: float) -> InvariantResult:
    tokens = sum(r[8] for r in ledger.rows)
    goodput = 1000.0 * tokens / max(1, horizon_ms)
    return InvariantResult(
        "sim_goodput_floor", goodput >= floor_tok_s,
        f"{goodput:.2f} tok/s virtual (floor {floor_tok_s})")


def no_silent_loss_ledger(trace: Trace,
                          ledger: ReplayLedger) -> InvariantResult:
    """Full-ledger accounting: one row per trace event, matched by id,
    and every non-ok row structured with a recognized prefix."""
    want = [e.eid for e in trace.events]
    got = [r[0] for r in ledger.rows]
    if want != got:
        return InvariantResult(
            "sim_no_silent_loss", False,
            f"event/row mismatch: {len(want)} events, {len(got)} rows")
    bad = 0
    detail = ""
    for r in ledger.rows:
        outcome, reason = r[3], r[4]
        if outcome == "ok":
            if reason not in _OK_REASONS:
                bad += 1
                detail = detail or f"ok row {r[0]} reason {reason!r}"
        elif outcome in ("shed", "deadline"):
            if not reason.startswith(STRUCTURED_ERROR_PREFIXES):
                bad += 1
                detail = detail or (f"{outcome} row {r[0]} "
                                    f"unstructured {reason!r}")
        else:
            bad += 1
            detail = detail or f"row {r[0]} unknown outcome {outcome!r}"
    return InvariantResult(
        "sim_no_silent_loss", bad == 0,
        detail or f"{len(got)} rows, all accounted and structured")


def tier_conservation(ladder) -> InvariantResult:
    census = ladder.census()
    return conservation(
        "sim_tier_conservation", census["seen"],
        {t: census[t] for t in (*TIERS, "dropped")})


def ledger_deterministic(a: ReplayLedger,
                         b: ReplayLedger) -> InvariantResult:
    ja, jb = a.to_json(), b.to_json()
    return InvariantResult(
        "sim_ledger_deterministic", ja == jb,
        f"digests {a.digest()} vs {b.digest()}, "
        f"{len(a)} vs {len(b)} rows"
        + ("" if ja == jb else " — NOT byte-identical"))


def sim_tree_conservation(trace: Trace,
                          ledger: ReplayLedger) -> InvariantResult:
    """Agent-tree lineage accounting (ISSUE 20): every ledger row's
    tree id matches the trace event it came from, per-tree node (row)
    counts equal per-tree trace event counts, and per-tree delivered
    tokens equal the trace-side recomputation (``max_new_tokens *
    max(1, consensus_k)`` on ok rows, 0 on shed/deadline). EXACT
    integer equality — never approximate; scenarios without tree
    streams pass vacuously."""
    by_eid = {e.eid: e for e in trace.events}
    want_count: dict = {}
    for e in trace.events:
        tid = tree_id_of(e)
        if tid:
            want_count[tid] = want_count.get(tid, 0) + 1
    got_count: dict = {}
    got_tokens: dict = {}
    want_tokens: dict = {}
    for r in ledger.rows:
        tid = r[9] if len(r) > 9 else ""
        e = by_eid.get(r[0])
        expect = tree_id_of(e) if e is not None else ""
        if tid != expect:
            return InvariantResult(
                "sim_tree_conservation", False,
                f"row {r[0]} tree id {tid!r} != trace {expect!r}")
        if not tid:
            continue
        got_count[tid] = got_count.get(tid, 0) + 1
        got_tokens[tid] = got_tokens.get(tid, 0) + r[8]
        want_tokens[tid] = want_tokens.get(tid, 0) + (
            e.max_new_tokens * max(1, e.consensus_k)
            if r[3] == "ok" else 0)
    if not want_count:
        return InvariantResult(
            "sim_tree_conservation", True, "no agent-tree events")
    if got_count != want_count:
        bad = sorted(set(want_count) ^ set(got_count)
                     | {t for t in want_count
                        if got_count.get(t) != want_count[t]})
        return InvariantResult(
            "sim_tree_conservation", False,
            f"node-count mismatch on trees {bad[:4]}")
    if got_tokens != want_tokens:
        bad = sorted(t for t in want_tokens
                     if got_tokens.get(t) != want_tokens[t])
        return InvariantResult(
            "sim_tree_conservation", False,
            f"token-sum mismatch on trees {bad[:4]}")
    return InvariantResult(
        "sim_tree_conservation", True,
        f"{len(want_count)} trees, {sum(want_count.values())} nodes, "
        f"{sum(got_tokens.values())} tokens reconciled exactly")


def temp0_spot_equal(samples_a: list, samples_b: list) -> InvariantResult:
    """Engine-backed spot check: both replays sampled the same events
    at temperature 0 and got bit-identical texts; any sampled failure
    is structured."""
    if not samples_a and not samples_b:
        return InvariantResult(
            "sim_temp0_spot_equal", True, "model-only replay, 0 samples")
    if samples_a != samples_b:
        return InvariantResult(
            "sim_temp0_spot_equal", False,
            f"sample divergence across replays "
            f"({len(samples_a)} vs {len(samples_b)})")
    for eid, ok, text in samples_a:
        if not ok and not text.startswith(STRUCTURED_ERROR_PREFIXES):
            return InvariantResult(
                "sim_temp0_spot_equal", False,
                f"sample {eid} unstructured failure {text[:80]!r}")
    return InvariantResult(
        "sim_temp0_spot_equal", True,
        f"{len(samples_a)} samples bit-identical across replays")


# -- scenario catalog ----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SimScenario:
    """One canonical trace pinned to a sized capacity model and
    floors. ``scale`` multiplies population sizes (tests keep the
    100k long tail at full size; bench smoke may shrink it)."""

    name: str
    description: str
    capacity: CapacityModel
    goodput_floor_tok_s: float
    # ((class, ttft budget ms, attainment floor), ...)
    slo: tuple
    engine_sampled: bool = False
    scale: float = 1.0


SIM_SCENARIOS = {
    "diurnal_mix": SimScenario(
        name="diurnal_mix",
        description=("multi-tenant diurnal curves, engine-sampled "
                     "spot checks"),
        capacity=CapacityModel(),
        goodput_floor_tok_s=0.5,
        slo=(("interactive", 1_500, 0.95), ("agent", 6_000, 0.90)),
        engine_sampled=True,
    ),
    "storm": SimScenario(
        name="storm",
        description=("burst storm over a deliberately small fleet: "
                     "the shed ladder must fire, batch first, while "
                     "the reserved pool protects interactive"),
        capacity=CapacityModel(
            decode_slots=2, reserved_interactive=1,
            prefill_tok_s=20_000.0, decode_tok_s=60.0),
        goodput_floor_tok_s=1.0,
        slo=(("interactive", 1_500, 0.70),),
    ),
    "agent_tree": SimScenario(
        name="agent_tree",
        description=("recursive spawn fan-outs with per-depth "
                     "consensus K, engine-sampled"),
        capacity=CapacityModel(),
        goodput_floor_tok_s=0.5,
        slo=(("agent", 6_000, 0.90),),
        engine_sampled=True,
    ),
    "longtail_ladder": SimScenario(
        name="longtail_ladder",
        description=("O(100k) mostly-hibernated sessions reactivating "
                     "through the full tier ladder at compressed time"),
        capacity=CapacityModel(),
        goodput_floor_tok_s=1.0,
        slo=(("interactive", 1_500, 0.90),),
    ),
}


@dataclasses.dataclass
class SimReport:
    name: str
    seed: int
    passed: bool
    invariants: list
    evidence: dict
    wall_s: float

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "passed": self.passed,
            "invariants": [r.as_dict() for r in self.invariants],
            "evidence": self.evidence,
            "wall_s": round(self.wall_s, 2),
        }


def run_sim_scenario(name: str, seed: int = 0, plane=None,
                     scale: Optional[float] = None) -> SimReport:
    """Generate the canonical trace, replay it TWICE at compressed
    time, and evaluate the full workload-invariant catalog. For
    engine-sampled scenarios a mock-device ClusterPlane is built (or
    pass ``plane`` to reuse one); model-only scenarios never touch a
    device. ``scale`` overrides the scenario's population scale (bench
    smoke shrinks the long tail). Both replays must agree
    byte-for-byte."""
    from quoracle_tpu.infra.flightrec import FLIGHT
    from quoracle_tpu.infra.telemetry import SIM_GATE_FAILURES

    sc = SIM_SCENARIOS[name]
    t0 = time.monotonic()
    spec = canonical_spec(
        name, seed=seed, scale=sc.scale if scale is None else scale)
    trace = generate(spec)
    SIM.note_trace(trace.stats())
    own_plane = None
    if sc.engine_sampled and plane is None:
        from quoracle_tpu.serving.cluster import ClusterPlane
        own_plane = plane = ClusterPlane.build(
            [MEMBER], replicas=1, disaggregate=False)
    try:
        sample_every = (max(1, len(trace) // 8)
                        if sc.engine_sampled else 0)
        drivers = []
        ledgers = []
        for _ in range(2):
            d = ReplayDriver(trace, capacity=sc.capacity, plane=plane,
                             member=MEMBER, sample_every=sample_every)
            ledgers.append(d.run())
            drivers.append(d)
        results = [ledger_deterministic(*ledgers),
                   no_silent_loss_ledger(trace, ledgers[0])]
        results.extend(slo_attainment(ledgers[0], sc.slo))
        results.append(goodput_floor(ledgers[0], spec.horizon_ms,
                                     sc.goodput_floor_tok_s))
        results.append(tier_conservation(drivers[0].ladder))
        results.append(sim_tree_conservation(trace, ledgers[0]))
        results.append(temp0_spot_equal(drivers[0].samples,
                                        drivers[1].samples))
    finally:
        if own_plane is not None:
            own_plane.close()
    passed = all(r.ok for r in results)
    if not passed:
        SIM_GATE_FAILURES.inc(scenario=name)
    report = SimReport(
        name=name, seed=seed, passed=passed, invariants=results,
        evidence={"trace": trace.stats(),
                  "ledger": ledgers[0].digest(),
                  "outcomes": ledgers[0].counts(),
                  "census": drivers[0].ladder.census(),
                  "samples": len(drivers[0].samples)},
        wall_s=time.monotonic() - t0)
    FLIGHT.record("sim_gate", name=name, seed=seed, passed=passed,
                  invariants=len(results))
    SIM.note_report(report.as_dict())
    return report
