"""Measured-profile sim calibration (ISSUE 17).

The fleet simulator (ISSUE 16) replays traces against a
:class:`~quoracle_tpu.sim.replay.CapacityModel` whose service-time
parameters were, until now, hand-sized per scenario. The chip-economics
plane (infra/costobs.py) measures the real plane's service rates as a
side effect of attribution — per-stage chip-seconds and the real tokens
that rode them. This module closes the loop:

* :func:`fit_capacity` — fit ``prefill_tok_s`` / ``decode_tok_s`` /
  per-rung ``restore_ms`` from one recorded :class:`ChipLedger`. The
  fit is the ledger's own semantics inverted: attribution splits each
  measured wall by real tokens, so ``stage tokens / stage chip-seconds``
  IS the effective per-slot service rate — a trace event's simulated
  service time under the fitted model equals the chip-time the ledger
  would have charged it. Stages with too few tokens keep the base
  parameter (a fit from noise is worse than a default), and the report
  says which.
* :func:`calibrate` — the same fit against the process's live ledgers
  (``costobs.ledgers()``), for operator use from a REPL or notebook.
* :func:`record_profile` — the measurement fixture: replay a trace
  under a ground-truth CapacityModel and charge a standalone ChipLedger
  exactly as the real plane would (prefill/decode walls by token rate,
  restore walls by rung). Calibrating from that ledger must recover the
  truth — the tier-1 gate's closed loop.
* :func:`ttft_gate` — the acceptance gate: replay the trace under the
  FITTED model and compare per-class TTFT quantiles of ok events
  against the measured ledger. Calibration is only trusted while the
  calibrated sim reproduces measured TTFT within tolerance
  (tests/test_costobs.py, tier-1).

Everything here is deterministic: pure arithmetic over recorded
integers, no wall clock, no RNG — two fits of one ledger are
bit-identical, like every other sim artifact.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from quoracle_tpu.infra.costobs import ChipLedger
from quoracle_tpu.sim.replay import CapacityModel, ReplayDriver, ReplayLedger
from quoracle_tpu.sim.workload import Trace

# Below this many charged tokens (or restore events) a stage's measured
# rate is noise — the fit keeps the base parameter and reports the
# stage as unfitted.
MIN_STAGE_TOKENS = 32
MIN_RESTORE_EVENTS = 4

# Per-class minimum ok-event count for a TTFT quantile to participate
# in the gate verdict (quantiles over a handful of samples gate nothing).
MIN_GATE_SAMPLES = 20

QUANTILES = (0.5, 0.9)


@dataclasses.dataclass(frozen=True)
class CalibrationReport:
    """One fit: the base model, the fitted model, and per-parameter
    provenance (measured vs kept-from-base)."""

    model: str
    base: CapacityModel
    fitted: CapacityModel
    fitted_params: tuple                  # names actually measured
    samples: dict                         # stage -> {tokens, chip_ms}

    def as_dict(self) -> dict:
        return {
            "model": self.model,
            "fitted_params": list(self.fitted_params),
            "prefill_tok_s": round(self.fitted.prefill_tok_s, 3),
            "decode_tok_s": round(self.fitted.decode_tok_s, 3),
            "restore_ms": {k: round(float(v), 3)
                           for k, v in self.fitted.restore_ms},
            "samples": self.samples,
        }


def fit_capacity(ledger: ChipLedger,
                 base: Optional[CapacityModel] = None) -> CalibrationReport:
    """Fit CapacityModel service parameters from one ChipLedger."""
    base = base or CapacityModel()
    stage_ns = ledger.stage_ns()
    stage_tokens = ledger.stage_tokens()
    fitted: list = []
    samples: dict = {}

    def rate(stage: str) -> Optional[float]:
        toks, ns = stage_tokens.get(stage, 0), stage_ns.get(stage, 0)
        samples[stage] = {"tokens": toks,
                          "chip_ms": round(ns / 1e6, 3)}
        if toks < MIN_STAGE_TOKENS or ns <= 0:
            return None
        return toks / (ns / 1e9)

    prefill = rate("prefill")
    decode = rate("decode")
    if prefill is not None:
        fitted.append("prefill_tok_s")
    if decode is not None:
        fitted.append("decode_tok_s")

    restore = {k: float(v) for k, v in base.restore_ms}
    for src, (n, ns) in sorted(ledger.restore_sources().items()):
        samples[f"restore:{src}"] = {"events": n,
                                     "chip_ms": round(ns / 1e6, 3)}
        if src in restore and n >= MIN_RESTORE_EVENTS:
            restore[src] = ns / 1e6 / n
            fitted.append(f"restore_ms:{src}")

    model = dataclasses.replace(
        base,
        prefill_tok_s=prefill if prefill is not None
        else base.prefill_tok_s,
        decode_tok_s=decode if decode is not None
        else base.decode_tok_s,
        restore_ms=tuple((k, restore[k]) for k, _ in base.restore_ms))
    return CalibrationReport(model=ledger.model, base=base, fitted=model,
                             fitted_params=tuple(fitted), samples=samples)


def calibrate(model: Optional[str] = None,
              base: Optional[CapacityModel] = None
              ) -> Optional[CalibrationReport]:
    """Fit from the process's live ledgers: the named model's, else the
    busiest. None when nothing has been charged yet."""
    from quoracle_tpu.infra import costobs
    ledgers = costobs.ledgers()
    if model is not None:
        led = ledgers.get(model)
    else:
        led = max(ledgers.values(), key=lambda l: l.busy_ns(),
                  default=None)
    if led is None or led.busy_ns() <= 0:
        return None
    return fit_capacity(led, base=base)


# ---------------------------------------------------------------------------
# Measurement fixture + acceptance gate
# ---------------------------------------------------------------------------


def record_profile(trace: Trace, capacity: CapacityModel,
                   model: str = "sim:profile") -> tuple:
    """Replay ``trace`` under ``capacity`` (the "real fleet") and charge
    a STANDALONE ChipLedger the way the live plane would: each ok
    event's prefill/decode wall at the true token rates, each restore at
    its rung penalty. Returns ``(chip_ledger, replay_ledger)`` — the
    measured profile and the measured TTFT distribution the gate
    compares against. The ledger is deliberately NOT registered in
    ``costobs.ledgers()`` — a recording fixture, not live state."""
    driver = ReplayDriver(trace, capacity=capacity)
    replay = driver.run()
    led = ChipLedger(model)
    restore_ms = dict(capacity.restore_ms)
    by_eid = {e.eid: e for e in trace.events}
    for eid, _t, _cls, outcome, _reason, _ttft, tier_from, _to, \
            tokens, _tree in replay.rows:
        if outcome != "ok":
            continue                      # shed work never ran on chips
        e = by_eid[eid]
        led.charge("prefill", e.prompt_tokens / capacity.prefill_tok_s,
                   [e.prompt_tokens], [("sim", e.cls, "-", "-")],
                   e.prompt_tokens)
        led.charge("decode", tokens / capacity.decode_tok_s,
                   [tokens], [("sim", e.cls, "-", "-")], tokens)
        rung = restore_ms.get(tier_from, 0)
        if rung:
            led.charge("restore", rung / 1e3, [1],
                       [("sim", e.cls, "-", "-")], 1)
            led.note_restore_source(tier_from, int(rung * 1e6))
    return led, replay


def ttft_quantiles(ledger: ReplayLedger,
                   qs: tuple = QUANTILES) -> dict:
    """{cls: {"n": ok events, "p50": ms, "p90": ms, ...}} over the
    ledger's ok rows (nearest-rank on the recorded integer µs — no
    interpolation, so two runs of one ledger agree bit-for-bit)."""
    by_cls: dict = {}
    for row in ledger.rows:
        if row[3] == "ok":
            by_cls.setdefault(row[2], []).append(row[5])
    out: dict = {}
    for cls, us in by_cls.items():
        us.sort()
        ent = {"n": len(us)}
        for q in qs:
            idx = min(len(us) - 1, int(q * len(us)))
            ent[f"p{int(q * 100)}"] = round(us[idx] / 1000.0, 3)
        out[cls] = ent
    return out


def ttft_gate(trace: Trace, measured: ReplayLedger,
              fitted: CapacityModel, tol: float = 0.35) -> dict:
    """Replay ``trace`` under the FITTED model and require every
    well-sampled class's TTFT quantiles to sit within ``tol`` relative
    error of the measured distribution. Returns a structured report —
    ``passed`` plus per-class/per-quantile deltas — the tier-1 test
    asserts on and /api/sim-style panels can render."""
    calibrated = ReplayDriver(trace, capacity=fitted).run()
    m_q, c_q = ttft_quantiles(measured), ttft_quantiles(calibrated)
    checks: list = []
    for cls in sorted(m_q):
        m, c = m_q[cls], c_q.get(cls)
        if m["n"] < MIN_GATE_SAMPLES or c is None:
            continue
        for q in QUANTILES:
            name = f"p{int(q * 100)}"
            mv, cv = m[name], (c or {}).get(name, 0.0)
            rel = abs(cv - mv) / max(mv, 1e-6)
            checks.append({"cls": cls, "q": name,
                           "measured_ms": mv, "calibrated_ms": cv,
                           "rel_err": round(rel, 4),
                           "ok": rel <= tol})
    return {"passed": bool(checks) and all(c["ok"] for c in checks),
            "tol": tol, "checks": checks,
            "measured": m_q, "calibrated": c_q}
