"""Compressed-time replay driver (ISSUE 16 tentpole, part b).

``ReplayDriver`` walks a trace event by event on a VIRTUAL clock — time
advances to the next arrival instead of sleeping — against a
deterministic capacity + tier-ladder model, and records every outcome
(admit / shed / deadline, modeled TTFT, tier transition, tokens) into a
:class:`ReplayLedger`. The model is pure arithmetic over the event
stream: no wall-clock reads feed any ledger field, so replaying the
same trace twice yields a BIT-identical serialized ledger — the
determinism contract tier-1 asserts (sim/gate.py).

The modeled serving plane:

* **capacity** — an exact FCFS k-server queue (per-slot free-time heap)
  with a reserved interactive sub-pool, per-class queue-wait shed
  bounds (batch sheds first, the shed ladder's shape), and per-event
  service time from prompt/decode token counts × consensus K;
* **tier ladder** — LRU session tiers with capacity cascades
  (resident → host → disk → prefixd → dropped), restore penalties per
  rung charged into TTFT, and a conservation census (every virtual
  session accounted — the hibernation-tier invariant's source);
* **forecast seam** — per-window traffic-mix priors offered to a
  dry-run FleetController through ``FleetSignals.forecast`` (shadow
  mode: recorded, never acted on — the predictive-policy down payment).

A real plane (mock-device ClusterPlane / FabricPlane, or a live fleet
via ``--sim-trace``) can ride along: every ``sample_every``-th event is
ALSO submitted as a temperature-0 request, and the collected texts feed
the temp-0 spot-check equality invariant. Samples never enter the
ledger — wall time stays out of the determinism contract.

``paced=True`` sleeps a bounded wall-clock scale between events (game
day against a live fleet); ledger fields are virtual either way, so
compressed and paced replays of the same trace are identical.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import logging
import time
from collections import OrderedDict
from typing import Optional

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.sim.workload import (
    CLASSES, Trace, event_prompt_text, tree_id_of,
)

logger = logging.getLogger(__name__)

TIERS = ("resident", "host", "disk", "prefixd")

# trace class → serving priority (serving/qos.Priority values)
CLASS_PRIORITY = {"interactive": 0, "agent": 1, "batch": 2}


@dataclasses.dataclass(frozen=True)
class CapacityModel:
    """The modeled fleet, in whole numbers a capacity planner would
    recognize. Defaults approximate a small disaggregated cluster; the
    canonical scenarios (sim/gate.py) size it per trace."""

    decode_slots: int = 32                # concurrent decode rows
    reserved_interactive: int = 8        # slots only interactive/agent use
    prefill_tok_s: float = 50_000.0       # aggregate prefill throughput
    decode_tok_s: float = 400.0           # per-row decode speed
    # queue-wait shed bounds per class (ms) — batch sheds first
    shed_wait_ms: tuple = (("interactive", 2_000), ("agent", 4_000),
                           ("batch", 1_000))
    # tier-ladder session capacities (cascade on overflow)
    resident_sessions: int = 512
    host_sessions: int = 4_096
    disk_sessions: int = 16_384
    prefixd_sessions: int = 16_384
    # restore penalty charged into TTFT per source rung (ms)
    restore_ms: tuple = (("host", 8), ("disk", 40), ("prefixd", 120))


class TierLadder:
    """Deterministic LRU model of the HBM→host→disk→prefixd ladder for
    O(100k) virtual sessions. A touch promotes to resident and cascades
    overflow down the rungs; past the last rung a session is DROPPED
    with a structured reason (never silently forgotten) and its next
    touch is a cold re-prefill. ``census()`` accounts every session
    ever seen — the conservation invariant's source of truth."""

    def __init__(self, cap: CapacityModel):
        self.caps = {"resident": cap.resident_sessions,
                     "host": cap.host_sessions,
                     "disk": cap.disk_sessions,
                     "prefixd": cap.prefixd_sessions}
        self.tiers: dict = {t: OrderedDict() for t in TIERS}
        self.dropped: set = set()
        self.seen = 0
        self.restores = {t: 0 for t in ("host", "disk", "prefixd")}
        self.demotions = {t: 0 for t in ("host", "disk", "prefixd")}
        self.drops = 0
        self.cold_reprefills = 0

    def touch(self, session: str) -> str:
        """Promote to resident; return the tier the session came FROM
        (``new`` for first sight, ``dropped`` for a cold re-prefill)."""
        for t in TIERS:
            if session in self.tiers[t]:
                if t == "resident":
                    self.tiers[t].move_to_end(session)
                    return "resident"
                del self.tiers[t][session]
                self.tiers["resident"][session] = True
                self.restores[t] += 1
                self._cascade()
                return t
        if session in self.dropped:
            self.dropped.discard(session)
            self.cold_reprefills += 1
            src = "dropped"
        else:
            self.seen += 1
            src = "new"
        self.tiers["resident"][session] = True
        self._cascade()
        return src

    def _cascade(self) -> None:
        for src, dst in (("resident", "host"), ("host", "disk"),
                         ("disk", "prefixd")):
            tier = self.tiers[src]
            while len(tier) > self.caps[src]:
                victim, _ = tier.popitem(last=False)
                self.tiers[dst][victim] = True
                self.demotions[dst] += 1
        last = self.tiers["prefixd"]
        while len(last) > self.caps["prefixd"]:
            victim, _ = last.popitem(last=False)
            self.dropped.add(victim)
            self.drops += 1

    def census(self) -> dict:
        c = {t: len(self.tiers[t]) for t in TIERS}
        c["dropped"] = len(self.dropped)
        c["seen"] = self.seen
        return c


class ReplayLedger:
    """Per-event outcomes, canonically serializable. One row per trace
    event: ``[eid, t_ms, cls, outcome, reason, ttft_us, tier_from,
    tier_to, tokens, tree]`` — ints and strings only, so the digest is
    a byte-level determinism check. ``tree`` (ISSUE 20) is the
    agent-tree lineage id for tree-stream events, empty otherwise; the
    sim_tree_conservation gate invariant reconciles it against the
    generated trace exactly."""

    def __init__(self):
        self.rows: list = []

    def append(self, eid: str, t_ms: int, cls: str, outcome: str,
               reason: str, ttft_us: int, tier_from: str,
               tier_to: str, tokens: int, tree: str = "") -> None:
        self.rows.append([eid, t_ms, cls, outcome, reason, ttft_us,
                          tier_from, tier_to, tokens, tree])

    def __len__(self) -> int:
        return len(self.rows)

    def to_json(self) -> str:
        return json.dumps({"version": 1, "rows": self.rows},
                          sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        h = hashlib.sha256()
        for r in self.rows:
            h.update(json.dumps(r, separators=(",", ":")).encode())
        return h.hexdigest()[:16]

    def counts(self) -> dict:
        c = {"ok": 0, "shed": 0, "deadline": 0}
        for r in self.rows:
            c[r[3]] = c.get(r[3], 0) + 1
        return c


class ReplayDriver:
    """One trace → one ledger. Single-threaded by design: the only lock
    involved is the process-wide ``SIM`` status board's (rank 3,
    bookkeeping only — nothing is called under it)."""

    def __init__(self, trace: Trace,
                 capacity: Optional[CapacityModel] = None,
                 plane=None, member: Optional[str] = None,
                 fleet=None, bus=None, paced: bool = False,
                 pace_scale: float = 10_000.0, pace_cap_ms: float = 5.0,
                 sample_every: int = 0, max_samples: int = 8,
                 forecast_windows: int = 8):
        self.trace = trace
        self.capacity = capacity or CapacityModel()
        self.plane = plane
        self.member = member
        self.fleet = fleet
        self.bus = bus
        self.paced = paced
        self.pace_scale = pace_scale
        self.pace_cap_ms = pace_cap_ms
        self.sample_every = sample_every
        self.max_samples = max_samples
        self.forecast_windows = max(1, forecast_windows)
        self.ladder = TierLadder(self.capacity)
        self.samples: list = []
        self.forecasts: list = []
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    # -- the modeled serving plane ---------------------------------------

    def _service_ms(self, e, tier_from: str) -> tuple:
        """(restore_ms, prefill_ms, decode_ms) for one event."""
        cap = self.capacity
        restore = dict(cap.restore_ms).get(tier_from, 0)
        prefill = 1000.0 * e.prompt_tokens / cap.prefill_tok_s
        decode = (1000.0 * e.max_new_tokens * max(1, e.consensus_k)
                  / cap.decode_tok_s)
        return float(restore), prefill, decode

    def run(self) -> ReplayLedger:
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import (
            SIM_EVENTS_TOTAL, SIM_GOODPUT, SIM_REPLAYS_TOTAL,
            SIM_SESSIONS, SIM_TTFT_MS,
        )

        cap = self.capacity
        mode = "paced" if self.paced else "compressed"
        FLIGHT.record("sim_replay_start", mode=mode,
                      events=len(self.trace),
                      trace=self.trace.digest())
        t_wall0 = time.monotonic()
        ledger = ReplayLedger()
        shed_wait = dict(cap.shed_wait_ms)
        # FCFS k-server free-time heaps: a shared pool every class uses
        # plus a reserved pool batch may not touch — the modeled shed
        # ladder's interactive protection
        shared = [0.0] * max(1, cap.decode_slots
                             - cap.reserved_interactive)
        reserved = [0.0] * max(0, cap.reserved_interactive)
        heapq.heapify(shared)
        heapq.heapify(reserved)
        ok_tokens = 0
        event_counts: dict = {}
        horizon = max(1, self.trace.spec.horizon_ms)
        window_ms = max(1, horizon // self.forecast_windows)
        window_end = window_ms
        window_counts = {c: 0 for c in CLASSES}
        prev_t = 0
        observe_stride = 16 if len(self.trace) > 10_000 else 1
        for idx, e in enumerate(self.trace.events):
            if self._stop:
                break
            if self.paced and e.t_ms > prev_t:
                # wall pacing only — no wall-clock value is recorded
                time.sleep(min(self.pace_cap_ms,
                               (e.t_ms - prev_t) / self.pace_scale)
                           / 1000.0)
            prev_t = e.t_ms
            while e.t_ms >= window_end:
                self._flush_forecast(window_end, window_ms,
                                     window_counts)
                window_counts = {c: 0 for c in CLASSES}
                window_end += window_ms
            window_counts[e.cls] += 1
            # admission against the modeled queue
            pool = shared
            if e.cls != "batch" and reserved and (
                    reserved[0] <= shared[0]):
                pool = reserved
            free = pool[0]
            start = max(float(e.t_ms), free)
            wait_ms = start - e.t_ms
            tier_from = self.ladder.touch(e.session)
            restore, prefill, decode = self._service_ms(e, tier_from)
            ttft_ms = wait_ms + restore + prefill
            if wait_ms > shed_wait.get(e.cls, 2_000):
                outcome, reason = "shed", "admission_rejected:queue_wait"
                ttft_ms, tokens = 0.0, 0
            elif e.deadline_ms and ttft_ms > e.deadline_ms:
                outcome = "deadline"
                reason = "deadline_exceeded:modeled_ttft"
                tokens = 0
            else:
                outcome, reason = "ok", ""
                if tier_from == "dropped":
                    reason = "cold_reprefill"
                tokens = e.max_new_tokens * max(1, e.consensus_k)
                ok_tokens += tokens
                heapq.heapreplace(pool, start + restore + prefill
                                  + decode)
            ledger.append(e.eid, e.t_ms, e.cls, outcome, reason,
                          int(round(ttft_ms * 1000.0)), tier_from,
                          "resident", tokens, tree_id_of(e))
            key = (e.stream.split(":", 1)[0], outcome)
            event_counts[key] = event_counts.get(key, 0) + 1
            if outcome == "ok" and idx % observe_stride == 0:
                SIM_TTFT_MS.observe(ttft_ms, cls=e.cls)
            self._maybe_sample(idx, e)
        self._flush_forecast(window_end, window_ms, window_counts)
        for (stream, outcome), n in sorted(event_counts.items()):
            SIM_EVENTS_TOTAL.inc(n, stream=stream, outcome=outcome)
        goodput = 1000.0 * ok_tokens / horizon
        SIM_GOODPUT.set(round(goodput, 3))
        census = self.ladder.census()
        for tier in (*TIERS, "dropped"):
            SIM_SESSIONS.set(census[tier], tier=tier)
        SIM_REPLAYS_TOTAL.inc(mode=mode, result="ok")
        wall_s = time.monotonic() - t_wall0
        summary = {
            "mode": mode, "events": len(ledger),
            "trace": self.trace.digest(), "ledger": ledger.digest(),
            "outcomes": ledger.counts(),
            "goodput_tok_s_virtual": round(goodput, 3),
            "census": census, "samples": len(self.samples),
            "forecasts": len(self.forecasts),
            "cold_reprefills": self.ladder.cold_reprefills,
            "restores": dict(self.ladder.restores),
            "demotions": dict(self.ladder.demotions),
            "events_per_wall_s": round(len(ledger)
                                       / max(1e-9, wall_s), 1),
            "compression_x": round(horizon / 1000.0
                                   / max(1e-9, wall_s), 1),
            "wall_s": round(wall_s, 3),
        }
        FLIGHT.record("sim_replay_end", **{
            k: summary[k] for k in ("mode", "events", "ledger",
                                    "outcomes", "wall_s")})
        if self.bus is not None:
            from quoracle_tpu.infra.bus import TOPIC_SIM
            try:
                self.bus.broadcast(TOPIC_SIM, {"type": "sim_replay",
                                               **summary})
            except Exception:             # noqa: BLE001 — best-effort
                logger.exception("sim replay broadcast failed")
        SIM.note_replay(summary)
        return ledger

    def _flush_forecast(self, window_end: int, window_ms: int,
                        counts: dict) -> None:
        """Offer the NEXT window's traffic-mix prior (computed from this
        window's arrivals) to the fleet policy — shadow mode."""
        from quoracle_tpu.infra.flightrec import FLIGHT
        span_s = window_ms / 1000.0
        mix = tuple(sorted(
            (c, round(n / span_s, 4)) for c, n in counts.items()))
        self.forecasts.append({"t_ms": window_end, "mix": dict(mix)})
        FLIGHT.record("sim_forecast", t_ms=window_end, mix=dict(mix))
        if self.fleet is not None:
            from quoracle_tpu.serving.fleet import FleetSignals
            try:
                self.fleet.tick(FleetSignals(replicas=(),
                                             forecast=mix))
            except Exception:             # noqa: BLE001 — shadow seam
                logger.exception("sim forecast tick failed")

    def _maybe_sample(self, idx: int, e) -> None:
        """Engine-backed spot check: every ``sample_every``-th event is
        also served for real at temperature 0. Texts are collected for
        the equality invariant; wall time never touches the ledger."""
        if (self.plane is None or self.sample_every <= 0
                or idx % self.sample_every != 0
                or len(self.samples) >= self.max_samples):
            return
        from quoracle_tpu.models.runtime import QueryRequest
        member = self.member
        if member is None:
            return
        req = QueryRequest(
            member, [{"role": "user", "content": event_prompt_text(e)}],
            temperature=0.0, max_tokens=8,
            priority=CLASS_PRIORITY.get(e.cls, 2), tenant=e.tenant)
        try:
            r = self.plane.query([req])[0]
            self.samples.append(
                (e.eid, bool(r.ok), r.text if r.ok else (r.error or "")))
        except Exception as exc:          # noqa: BLE001 — structured
            self.samples.append((e.eid, False, f"{type(exc).__name__}"))


class SimStatus:
    """Process-wide status board behind ``GET /api/sim`` and the
    /telemetry panel — the sim twin of ``CHAOS.status()``. Pure
    bookkeeping under the rank-3 ``sim.replay`` lock; nothing else is
    ever called while it is held."""

    def __init__(self):
        self._lock = named_lock("sim.replay")
        self._trace: Optional[dict] = None
        self._last_replay: Optional[dict] = None
        self._last_report: Optional[dict] = None

    def note_trace(self, stats: dict) -> None:
        with self._lock:
            self._trace = dict(stats)

    def note_replay(self, summary: dict) -> None:
        with self._lock:
            self._last_replay = dict(summary)

    def note_report(self, report: dict) -> None:
        with self._lock:
            self._last_report = dict(report)

    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": (self._trace is not None
                            or self._last_replay is not None
                            or self._last_report is not None),
                "trace": self._trace,
                "last_replay": self._last_replay,
                "last_report": self._last_report,
            }


SIM = SimStatus()
