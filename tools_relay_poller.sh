#!/bin/bash
# Relay poller (VERDICT r4 item 1): poll the loopback relay all round; the
# moment the chip answers, run the full bench and write the artifact
# immediately so a later relay death can't erase it. Calibration and the
# long-context sweep run AFTER the bench record is safe: in the 03:45 UTC
# r5 window calibration ran first, OOMed mid-sweep (since fixed), and the
# relay wedged before bench.py got a single config out — the primary
# record must never queue behind a bonus measurement again.
#
# Log: /root/repo/RELAY_POLL_r05.log (one line per probe; goes into the
# BENCH artifact if the relay never answers).
# Success artifacts: /root/repo/BENCH_r05_live.json, then calib_v5e.json
# (QUORACLE_PAGED_CALIB gates) + LONGCTX_r05.json as bonus captures.

cd /root/repo
LOG=RELAY_POLL_r05.log
PORTS="8082 8083 8087 8092"

probe_ports() {
    for p in $PORTS; do
        if timeout 2 bash -c "echo > /dev/tcp/127.0.0.1/$p" 2>/dev/null; then
            return 0
        fi
    done
    return 1
}

echo "$(date -u +%FT%TZ) poller start (pid $$)" >> "$LOG"
while true; do
    if probe_ports; then
        echo "$(date -u +%FT%TZ) relay port OPEN — probing device" >> "$LOG"
        # Confirm the device actually answers (a listening port is necessary
        # but not sufficient), using bench.py's own SIGTERM-safe probe.
        if timeout 400 python - >> "$LOG" 2>&1 <<'EOF'
import sys
sys.path.insert(0, "/root/repo")
import bench
p = bench.probe_device(300.0)
print("device probe:", p)
sys.exit(0 if p.get("ok") else 1)
EOF
        then
            echo "$(date -u +%FT%TZ) DEVICE LIVE — running bench (record first)" >> "$LOG"
            timeout 5400 python bench.py > /root/repo/BENCH_r05_live.json 2>> "$LOG"
            echo "$(date -u +%FT%TZ) bench rc=$? artifact=BENCH_r05_live.json" >> "$LOG"
            if python - <<'EOF'
import json
d = json.load(open("/root/repo/BENCH_r05_live.json"))
ok = (not d.get("device_unavailable")) and d.get("value")
raise SystemExit(0 if ok else 1)
EOF
            then
                echo "$(date -u +%FT%TZ) BENCH SUCCESS — chip-verified record captured" >> "$LOG"
                git add BENCH_r05_live.json RELAY_POLL_r05.log 2>/dev/null
                git -c user.name=distsys-graft -c user.email=graft@localhost \
                    commit -m "Chip-verified BENCH_r05_live artifact captured by relay poller" >> "$LOG" 2>&1
                # Bonus captures now that the record is safe.
                timeout 2400 python -m quoracle_tpu.tools.calibrate_paged \
                    --out /root/repo/calib_v5e.json >> "$LOG" 2>&1 \
                    && echo "$(date -u +%FT%TZ) calibration written" >> "$LOG" \
                    || echo "$(date -u +%FT%TZ) calibration FAILED (bench record already safe)" >> "$LOG"
                timeout 1800 python -m quoracle_tpu.tools.bench_longctx \
                    --resident 16384 --rounds 3 \
                    > /root/repo/LONGCTX_r05.json 2>> "$LOG" \
                    && echo "$(date -u +%FT%TZ) longctx captured" >> "$LOG" \
                    || echo "$(date -u +%FT%TZ) longctx FAILED (bench record already safe)" >> "$LOG"
                git add calib_v5e.json LONGCTX_r05.json RELAY_POLL_r05.log 2>/dev/null
                git -c user.name=distsys-graft -c user.email=graft@localhost \
                    commit -m "Post-bench chip captures: paged-gate calibration + long-context sweep" >> "$LOG" 2>&1
                echo "$(date -u +%FT%TZ) poller entering idle heartbeat" >> "$LOG"
                while true; do sleep 3600; echo "$(date -u +%FT%TZ) heartbeat (record already captured)" >> "$LOG"; done
            else
                echo "$(date -u +%FT%TZ) bench artifact not clean; will retry next poll" >> "$LOG"
            fi
        else
            echo "$(date -u +%FT%TZ) port open but device probe failed" >> "$LOG"
        fi
    else
        echo "$(date -u +%FT%TZ) relay dead (all ports closed)" >> "$LOG"
    fi
    sleep 570
done
