#!/bin/bash
# One-shot on the live chip: bench FIRST and git-commit the artifact the
# moment it is clean, THEN calibration + longctx as bonus captures —
# mirroring tools_relay_poller.sh. (ADVICE r5 medium: the old ordering ran
# the 2400 s calibration sweep before bench.py, and when the relay wedged
# mid-calibration the round lost its primary bench record entirely; the
# header claimed "commit immediately" but the script never committed.)
cd /root/repo
LOG=RELAY_POLL_r08.log
echo "$(date -u +%FT%TZ) direct run: device confirmed live (probe ok)" >> "$LOG"

# Primary record first. If a previous run left calibration gates behind,
# use them; their absence just means the paged direct paths stay off.
# The artifact carries config 9 (consensus round/decide p50/p95 from the
# infra/telemetry.py histograms), config 10 (resource observability,
# ISSUE 3: HBM headroom, compile hit-rate, queue-depth p95 under a
# sustained continuous-batching load), config 11 (serving QoS, ISSUE 4:
# INTERACTIVE p95 under 4x overload with QoS on/off, shed rate and
# structured-reject accounting), and config 12 (consensus quality,
# ISSUE 5: decide p50/p95 with the scorecard/audit layer on vs off, and
# the emitted vote entropy / winner margin for the temp-0 pool); config
# 10's sample timeline lands in the sidecar RESOURCES_r08_live.json and
# config 12's audit records + scorecards in QUALITY_r08_live.json, both
# committed with the bench record.
[ -f /root/repo/calib_v5e.json ] && export QUORACLE_PAGED_CALIB=/root/repo/calib_v5e.json
export QUORACLE_BENCH_RESOURCES=/root/repo/RESOURCES_r08_live.json
export QUORACLE_BENCH_QUALITY=/root/repo/QUALITY_r08_live.json
timeout 5400 python bench.py > /root/repo/BENCH_r08_live.json 2>> "$LOG"
rc=$?
echo "$(date -u +%FT%TZ) bench rc=$rc artifact=BENCH_r08_live.json" >> "$LOG"
if [ "$rc" -eq 0 ] && python - <<'EOF'
import json
d = json.load(open("/root/repo/BENCH_r08_live.json"))
ok = (not d.get("device_unavailable")) and d.get("value")
raise SystemExit(0 if ok else 1)
EOF
then
    echo "$(date -u +%FT%TZ) BENCH SUCCESS — committing the record" >> "$LOG"
    git add BENCH_r08_live.json RESOURCES_r08_live.json \
        QUALITY_r08_live.json "$LOG" 2>/dev/null
    git -c user.name=distsys-graft -c user.email=graft@localhost \
        commit -m "Chip-verified BENCH_r08_live artifact (direct run)" >> "$LOG" 2>&1 \
        || echo "$(date -u +%FT%TZ) commit failed (artifact still on disk)" >> "$LOG"
else
    echo "$(date -u +%FT%TZ) bench artifact not clean; bonus captures may still run" >> "$LOG"
fi

# Bonus captures — the primary record is already safe (or already failed
# on its own terms); a relay death here can no longer erase it.
timeout 2400 python -m quoracle_tpu.tools.calibrate_paged \
    --out /root/repo/calib_v5e.json >> "$LOG" 2>&1 \
    && echo "$(date -u +%FT%TZ) calibration written" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) calibration FAILED (bench record already safe)" >> "$LOG"
timeout 1800 python -m quoracle_tpu.tools.bench_longctx \
    --resident 16384 --rounds 3 \
    > /root/repo/LONGCTX_r08.json 2>> "$LOG" \
    && echo "$(date -u +%FT%TZ) longctx captured" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) longctx FAILED (bench record already safe)" >> "$LOG"
git add calib_v5e.json LONGCTX_r08.json "$LOG" 2>/dev/null
git -c user.name=distsys-graft -c user.email=graft@localhost \
    commit -m "Post-bench chip captures: paged-gate calibration + long-context sweep" >> "$LOG" 2>&1 \
    || true
