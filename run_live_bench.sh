#!/bin/bash
# One-shot on the live chip: bench FIRST and git-commit the artifact the
# moment it is clean, THEN calibration + longctx as bonus captures —
# mirroring tools_relay_poller.sh. (ADVICE r5 medium: the old ordering ran
# the 2400 s calibration sweep before bench.py, and when the relay wedged
# mid-calibration the round lost its primary bench record entirely; the
# header claimed "commit immediately" but the script never committed.)
cd /root/repo
LOG=RELAY_POLL_r09.log
echo "$(date -u +%FT%TZ) direct run: device confirmed live (probe ok)" >> "$LOG"

# Primary record first. If a previous run left calibration gates behind,
# use them; their absence just means the paged direct paths stay off.
# The artifact carries configs 9-12 (telemetry / resources / QoS /
# quality, see r08) plus the ISSUE 6 speculative rows: config 7 now adds
# the realized trained-draft projection (ceiling x the SPECULATIVE
# artifact's measured acceptance, greedy-equal asserted) and config 13
# measures the continuous+QoS serving path with speculation on vs off
# (decode ms/token, tokens/round, acceptance p50, fallback counts,
# temp-0 on/off bit-equality). Config 13's per-row detail lands in the
# SPEC_r09_live.json sidecar, committed with the bench record alongside
# the RESOURCES/QUALITY sidecars.
[ -f /root/repo/calib_v5e.json ] && export QUORACLE_PAGED_CALIB=/root/repo/calib_v5e.json
export QUORACLE_BENCH_RESOURCES=/root/repo/RESOURCES_r09_live.json
export QUORACLE_BENCH_QUALITY=/root/repo/QUALITY_r09_live.json
export QUORACLE_BENCH_SPEC=/root/repo/SPEC_r09_live.json
timeout 5400 python bench.py > /root/repo/BENCH_r09_live.json 2>> "$LOG"
rc=$?
echo "$(date -u +%FT%TZ) bench rc=$rc artifact=BENCH_r09_live.json" >> "$LOG"
if [ "$rc" -eq 0 ] && python - <<'EOF'
import json
d = json.load(open("/root/repo/BENCH_r09_live.json"))
ok = (not d.get("device_unavailable")) and d.get("value")
raise SystemExit(0 if ok else 1)
EOF
then
    echo "$(date -u +%FT%TZ) BENCH SUCCESS — committing the record" >> "$LOG"
    git add BENCH_r09_live.json RESOURCES_r09_live.json \
        QUALITY_r09_live.json SPEC_r09_live.json "$LOG" 2>/dev/null
    git -c user.name=distsys-graft -c user.email=graft@localhost \
        commit -m "Chip-verified BENCH_r09_live artifact (direct run)" >> "$LOG" 2>&1 \
        || echo "$(date -u +%FT%TZ) commit failed (artifact still on disk)" >> "$LOG"
else
    echo "$(date -u +%FT%TZ) bench artifact not clean; bonus captures may still run" >> "$LOG"
fi

# Bonus captures — the primary record is already safe (or already failed
# on its own terms); a relay death here can no longer erase it. The
# draft-training smoke (tools/train_draft.py --check) runs first: it is
# minutes-scale and guards the SPECULATIVE acceptance floor config 7's
# realized row depends on.
timeout 900 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m quoracle_tpu.tools.train_draft --check \
    > /root/repo/SPEC_CHECK_r09.json 2>> "$LOG" \
    && echo "$(date -u +%FT%TZ) draft check passed" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) draft check FAILED (bench record already safe)" >> "$LOG"
timeout 2400 python -m quoracle_tpu.tools.calibrate_paged \
    --out /root/repo/calib_v5e.json >> "$LOG" 2>&1 \
    && echo "$(date -u +%FT%TZ) calibration written" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) calibration FAILED (bench record already safe)" >> "$LOG"
timeout 1800 python -m quoracle_tpu.tools.bench_longctx \
    --resident 16384 --rounds 3 \
    > /root/repo/LONGCTX_r09.json 2>> "$LOG" \
    || echo "$(date -u +%FT%TZ) longctx FAILED (bench record already safe)" >> "$LOG"
git add calib_v5e.json LONGCTX_r09.json SPEC_CHECK_r09.json "$LOG" 2>/dev/null
git -c user.name=distsys-graft -c user.email=graft@localhost \
    commit -m "Post-bench chip captures: draft check + paged-gate calibration + long-context sweep" >> "$LOG" 2>&1 \
    || true
