#!/bin/bash
# One-shot: calibration sweep + full bench on the live chip, commit immediately.
cd /root/repo
LOG=RELAY_POLL_r05.log
echo "$(date -u +%FT%TZ) direct run: device confirmed live (probe ok)" >> "$LOG"
timeout 2400 python -m quoracle_tpu.tools.calibrate_paged \
    --out /root/repo/calib_v5e.json >> "$LOG" 2>&1 \
    && echo "$(date -u +%FT%TZ) calibration written" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) calibration FAILED (continuing to bench)" >> "$LOG"
export QUORACLE_PAGED_CALIB=/root/repo/calib_v5e.json
timeout 5400 python bench.py > /root/repo/BENCH_r05_live.json 2>> "$LOG"
echo "$(date -u +%FT%TZ) bench rc=$? artifact=BENCH_r05_live.json" >> "$LOG"
