#!/bin/bash
# One-shot on the live chip: bench FIRST and git-commit the artifact the
# moment it is clean, THEN calibration + longctx as bonus captures —
# mirroring tools_relay_poller.sh. (ADVICE r5 medium: the old ordering ran
# the 2400 s calibration sweep before bench.py, and when the relay wedged
# mid-calibration the round lost its primary bench record entirely; the
# header claimed "commit immediately" but the script never committed.)
cd /root/repo
LOG=RELAY_POLL_r22.log
echo "$(date -u +%FT%TZ) direct run: device confirmed live (probe ok)" >> "$LOG"

# Primary record first. If a previous run left calibration gates behind,
# use them; their absence just means the paged direct paths stay off —
# the UNIFIED ragged kernel (ISSUE 8) is ON by default on TPU either
# way (gather is the measured fallback; calibrate_paged below records
# the unified-vs-gather crossover per geometry). The artifact carries
# configs 9-12 (telemetry / resources / QoS / quality, see r08), the
# ISSUE 6 speculative rows (configs 7 + 13, r09), the ISSUE 7 tiered-KV
# row (config 14, r10), and the ISSUE 8 ragged-serving row (r11):
# config 15 drives mixed short-interactive + long-agent traffic through
# continuous batching unified vs gather — tokens/sec/chip, steady-state
# compile count (the batch×prompt bucket matrix vs token-budget
# buckets), real-vs-padded chunk tokens, decode HBM high-water, and the
# temp-0 equality gate. The r12 ISSUE 10 disaggregated-plane
# row: config 16 serves mixed interactive+agent traffic through one
# monolithic continuous replica vs a 2-replica prefill/decode
# cluster on the same device budget — tokens/sec/chip, interactive
# TTFT p95, KV-handoff p95 vs the cold re-prefill it replaces, and
# the temp-0 equality gate. NEW in r13 the ISSUE 11 chaos row:
# config 17 arms the storm fault mix (admission/router signal
# drop+delay, decode-replica death mid-row, tier-restore failures)
# against a 3-replica prefill/decode cluster at the same offered load
# chaos on vs off — goodput delta, interactive p95 during recovery,
# and the machine-checked invariant verdicts (zero silent loss,
# structured failures only, temp-0 survivor equality). In r14 the
# ISSUE 12 fabric row landed: config 18 runs the same disaggregated traffic
# through an in-process ClusterPlane vs a prefill+decode FabricPlane
# over the loopback wire (handoff p95 + per-row serialization
# overhead, temp-0 equality ASSERT), measures the fleet prefix hit
# rate cold-start with vs without prefixd, and front-door throughput
# at N loopback peers; detail in FABRIC_r22_live.json
# (QUORACLE_BENCH_FABRIC). In r15 config 19 landed — quantized
# serving (int8 weights + int8 KV pages): byte-rate/handoff/spill
# ratios, tokens/sec and scorecard deltas quantized vs not, with a
# self-consistency assert; detail in QUANT_r22_live.json
# (QUORACLE_BENCH_QUANT). In r16 config 20 landed — the elastic fleet
# controller (ISSUE 14): the same mixed traffic through a 3-replica
# prefill/decode QoS cluster static vs scale events forced
# mid-traffic (policy scale-up, forced drain with live session
# migration, re-tier round trip, scale-down) — goodput delta, SLO
# burn during the drain/re-tier window, sessions migrated/sec, and
# the temp-0 equality assert; detail in FLEET_r22_live.json
# (QUORACLE_BENCH_FLEET). In r17 config 21 landed — fleet observability
# (ISSUE 15): the same disaggregated traffic through a loopback
# prefill+decode fabric tracing off vs on (tokens/sec delta + temp-0
# equality ASSERT), one traced session's cross-peer TTFT
# decomposition (stages sum to the door-observed wall), and the
# metrics-federation sweep wall with rollup quantiles checked against
# the lossless-merge oracle; detail in FLEETOBS_r22_live.json
# (QUORACLE_BENCH_FLEETOBS). In r18 config 22 landed — the fleet
# simulator (ISSUE 16): the canonical workload traces (diurnal mix,
# burst storm, agent tree, 100k-session long-tail ladder) generated
# from a fixed seed and replayed twice each through the workload
# invariant gate at compressed time — replay events/sec, compression
# factor, outcome mixes, the long-tail tier census, and the ledger
# digests that witness determinism across revisions; detail in
# SIM_r22_live.json (QUORACLE_BENCH_SIM). In r19 config 23 landed —
# the chip-economics plane (ISSUE 17): real decides with cost
# accounting off vs on (tokens/sec delta + temp-0 equality ASSERT),
# the ON window's per-stage chip-second decomposition with the
# exact-sum invariant re-checked at bench scale, best MFU per
# compiled program with cliff counts, and the sim-calibration loop
# fitted from the live ledger profile gated on reproducing measured
# TTFT quantiles; detail in COST_r22_live.json
# (QUORACLE_BENCH_COST). In r20 config 24 landed — the liveness &
# hotspot plane (ISSUE 18): real decides with introspect off vs
# default vs aggressive sampling (temp-0 equality ASSERT), the
# profiler's SELF-MEASURED overhead fraction gated at 1% for the
# default rate, the wait-state decomposition totals (named waits +
# exact remainder sum to each row's wall), heartbeat deltas and
# stall-detector status; detail in INTROSPECT_r22_live.json
# (QUORACLE_BENCH_INTROSPECT). In r21 config 25 landed — the serving
# flywheel (ISSUE 19): one full capture → train → evaluate → promote
# cycle on the live chip — the same temp-0 rows through the
# continuous self-draft spec path with the replay capture plane off
# vs on (BIT-EQUALITY ASSERT + tokens/sec delta pricing the tap), a
# distillation cycle's held-out replay acceptance before/after
# through the real verify_chunk path, and a live hot-swap promotion
# with rows IN FLIGHT (every row must land — swap downtime == 0
# ASSERT — plus the promoted-draft tokens/sec uplift); detail in
# FLYWHEEL_r22_live.json (QUORACLE_BENCH_FLYWHEEL). NEW in r22:
# config 26 — the session-graph plane (ISSUE 20): real decides under
# a stamped agent-tree lineage with treeobs off vs on (temp-0
# decisions BIT-EQUAL ASSERT — the plane is observed-only — plus the
# tokens/sec delta pricing the bookkeeping), the exact
# rollup-conservation recheck on the assembled /api/tree view
# (recursive subtree totals == flat node sums, exact integers) with
# the fleet-wide assembly wall, and the critical-path column over
# every tree in the canonical agent-tree sim trace; detail in
# TREEOBS_r22_live.json (QUORACLE_BENCH_TREEOBS). Config 15's
# detail lands in the RAGGED_r22_live.json sidecar, config 16's in
# CLUSTER_r22_live.json, config 17's in CHAOS_r22_live.json,
# committed with the bench record alongside the
# RESOURCES/QUALITY/SPEC/KVTIER sidecars.
[ -f /root/repo/calib_v5e.json ] && export QUORACLE_PAGED_CALIB=/root/repo/calib_v5e.json
export QUORACLE_BENCH_RESOURCES=/root/repo/RESOURCES_r22_live.json
export QUORACLE_BENCH_QUALITY=/root/repo/QUALITY_r22_live.json
export QUORACLE_BENCH_SPEC=/root/repo/SPEC_r22_live.json
export QUORACLE_BENCH_KV=/root/repo/KVTIER_r22_live.json
export QUORACLE_BENCH_RAGGED=/root/repo/RAGGED_r22_live.json
export QUORACLE_BENCH_CLUSTER=/root/repo/CLUSTER_r22_live.json
export QUORACLE_BENCH_CHAOS=/root/repo/CHAOS_r22_live.json
export QUORACLE_BENCH_FABRIC=/root/repo/FABRIC_r22_live.json
export QUORACLE_BENCH_QUANT=/root/repo/QUANT_r22_live.json
export QUORACLE_BENCH_FLEET=/root/repo/FLEET_r22_live.json
export QUORACLE_BENCH_FLEETOBS=/root/repo/FLEETOBS_r22_live.json
export QUORACLE_BENCH_SIM=/root/repo/SIM_r22_live.json
export QUORACLE_BENCH_COST=/root/repo/COST_r22_live.json
export QUORACLE_BENCH_INTROSPECT=/root/repo/INTROSPECT_r22_live.json
export QUORACLE_BENCH_FLYWHEEL=/root/repo/FLYWHEEL_r22_live.json
export QUORACLE_BENCH_TREEOBS=/root/repo/TREEOBS_r22_live.json
timeout 5400 python bench.py > /root/repo/BENCH_r22_live.json 2>> "$LOG"
rc=$?
echo "$(date -u +%FT%TZ) bench rc=$rc artifact=BENCH_r22_live.json" >> "$LOG"
if [ "$rc" -eq 0 ] && python - <<'EOF'
import json
d = json.load(open("/root/repo/BENCH_r22_live.json"))
ok = (not d.get("device_unavailable")) and d.get("value")
raise SystemExit(0 if ok else 1)
EOF
then
    echo "$(date -u +%FT%TZ) BENCH SUCCESS — committing the record" >> "$LOG"
    git add BENCH_r22_live.json RESOURCES_r22_live.json \
        QUALITY_r22_live.json SPEC_r22_live.json \
        KVTIER_r22_live.json RAGGED_r22_live.json \
        CLUSTER_r22_live.json CHAOS_r22_live.json \
        FABRIC_r22_live.json QUANT_r22_live.json \
        FLEET_r22_live.json FLEETOBS_r22_live.json \
        SIM_r22_live.json COST_r22_live.json \
        INTROSPECT_r22_live.json FLYWHEEL_r22_live.json \
        TREEOBS_r22_live.json \
        "$LOG" 2>/dev/null
    git -c user.name=distsys-graft -c user.email=graft@localhost \
        commit -m "Chip-verified BENCH_r22_live artifact (direct run)" >> "$LOG" 2>&1 \
        || echo "$(date -u +%FT%TZ) commit failed (artifact still on disk)" >> "$LOG"
else
    echo "$(date -u +%FT%TZ) bench artifact not clean; bonus captures may still run" >> "$LOG"
fi

# Bonus captures — the primary record is already safe (or already failed
# on its own terms); a relay death here can no longer erase it. The
# draft-training smoke (tools/train_draft.py --check) runs first: it is
# minutes-scale and guards the SPECULATIVE acceptance floor config 7's
# realized row depends on.
timeout 900 env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m quoracle_tpu.tools.train_draft --check \
    > /root/repo/SPEC_CHECK_r22.json 2>> "$LOG" \
    && echo "$(date -u +%FT%TZ) draft check passed" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) draft check FAILED (bench record already safe)" >> "$LOG"
timeout 2400 python -m quoracle_tpu.tools.calibrate_paged \
    --out /root/repo/calib_v5e.json >> "$LOG" 2>&1 \
    && echo "$(date -u +%FT%TZ) calibration written" >> "$LOG" \
    || echo "$(date -u +%FT%TZ) calibration FAILED (bench record already safe)" >> "$LOG"
timeout 1800 python -m quoracle_tpu.tools.bench_longctx \
    --resident 16384 --rounds 3 \
    > /root/repo/LONGCTX_r22.json 2>> "$LOG" \
    || echo "$(date -u +%FT%TZ) longctx FAILED (bench record already safe)" >> "$LOG"
git add calib_v5e.json LONGCTX_r22.json SPEC_CHECK_r22.json "$LOG" 2>/dev/null
git -c user.name=distsys-graft -c user.email=graft@localhost \
    commit -m "Post-bench chip captures: draft check + paged-gate calibration + long-context sweep" >> "$LOG" 2>&1 \
    || true
